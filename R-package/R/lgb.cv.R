# lgb.cv: reference-compatible cross-validation
# (R-package/R/lgb.cv.R:81-304 surface) over the CLI transport.
#
# Each fold trains a full CLI run; per-iteration metrics are merged to
# mean +/- sd exactly like lgb.merge.cv.result (lgb.cv.R:430-475).
# early_stopping_rounds is applied to the merged mean curve after the
# folds finish — the selected best_iter matches the reference's
# in-the-loop stopping; only the wasted tail-training differs.

lgb.cv <- function(params = list(),
                   data,
                   nrounds = 10,
                   nfold = 3,
                   label = NULL,
                   weight = NULL,
                   obj = NULL,
                   eval = NULL,
                   verbose = 1,
                   record = TRUE,
                   eval_freq = 1L,
                   showsd = TRUE,
                   stratified = TRUE,
                   folds = NULL,
                   init_model = NULL,
                   colnames = NULL,
                   categorical_feature = NULL,
                   early_stopping_rounds = NULL,
                   callbacks = list(),
                   ...) {
  params <- append(params, list(...))
  if (!is.null(obj)) params$objective <- obj   # folds consult the objective
  if (!lgb.is.Dataset(data)) {
    if (is.null(label)) {
      stop("lgb.cv: data must be an lgb.Dataset, or supply label= with a ",
           "matrix")
    }
    data <- lgb.Dataset(data, info = list(label = label, weight = weight))
  }
  if (is.character(data$raw_data)) {
    stop("lgb.cv: file-backed datasets cannot be fold-sliced; load the ",
         "data into a matrix first")
  }
  y <- data$info$label
  n <- nrow(as.matrix(data$raw_data))
  if (is.null(folds)) {
    folds <- generate.cv.folds(nfold, n, stratified, y,
                               data$info$group, params)
  }
  nfold <- length(folds)

  per_fold <- vector("list", nfold)
  boosters <- vector("list", nfold)
  for (k in seq_len(nfold)) {
    test_idx <- folds[[k]]
    train_idx <- setdiff(seq_len(n), test_idx)
    pair <- .lgbtpu_cv_split(data, train_idx, test_idx)
    dtrain <- pair$train
    dvalid <- pair$valid
    bst <- lgb.train(params = params, data = dtrain, nrounds = nrounds,
                     valids = list(valid = dvalid), obj = obj, eval = eval,
                     verbose = 0, record = TRUE, eval_freq = 1L,
                     init_model = init_model, colnames = colnames,
                     categorical_feature = categorical_feature)
    per_fold[[k]] <- bst$record_evals[["valid"]]
    boosters[[k]] <- list(booster = bst)
  }

  # merge: mean + sd across folds per metric per iteration; folds can
  # in principle log different iteration counts (aborted runs), so
  # align on the shortest rather than letting matrix() recycle
  metrics <- names(per_fold[[1]])
  record_evals <- list(valid = list())
  for (m in metrics) {
    cols <- lapply(per_fold, function(r) unlist(r[[m]]$eval))
    n_it <- min(vapply(cols, length, integer(1)))
    vals <- vapply(cols, function(v) v[seq_len(n_it)], numeric(n_it))
    vals <- matrix(vals, nrow = n_it)
    means <- rowMeans(vals)
    sds <- apply(vals, 1, stats::sd)
    record_evals$valid[[m]] <- list(eval = as.list(means),
                                    eval_err = as.list(sds))
  }

  cvm <- new.env(parent = emptyenv())
  cvm$boosters <- boosters
  cvm$record_evals <- if (record) record_evals else list()
  cvm$best_iter <- -1L
  cvm$best_score <- NA_real_
  if (length(metrics)) {
    first <- metrics[1]
    means <- unlist(record_evals$valid[[first]]$eval)
    higher_better <- .lgbtpu_higher_better(first)
    best <- if (higher_better) which.max(means) else which.min(means)
    if (!is.null(early_stopping_rounds)) {
      # first iteration whose following early_stopping_rounds iterations
      # fail to improve (the reference's cb.early.stop over fold means)
      run_best <- if (higher_better) cummax(means) else cummin(means)
      stall <- which(seq_along(means) - match(run_best, run_best) >=
                       early_stopping_rounds)
      if (length(stall)) {
        best <- match(run_best[stall[1]], means)
      }
    }
    cvm$best_iter <- as.integer(best)
    cvm$best_score <- means[best]
  }
  if (verbose > 0 && length(metrics)) {
    for (i in seq(1, nrounds, by = max(1L, as.integer(eval_freq)))) {
      parts <- vapply(metrics, function(m) {
        e <- record_evals$valid[[m]]
        if (i > length(e$eval)) return(NA_character_)
        sprintf("valid %s: %g%s", m, e$eval[[i]],
                if (showsd) sprintf(" + %g", e$eval_err[[i]]) else "")
      }, character(1))
      parts <- parts[!is.na(parts)]
      if (length(parts)) cat(sprintf("[%d]\t%s\n", i,
                                     paste(parts, collapse = "\t")))
    }
  }
  structure(cvm, class = "lgb.CVBooster")
}

# Reference generate.cv.folds / lgb.stratified.folds (lgb.cv.R:306-428)
# in base R: stratified folds shuffle within sorted-label groups;
# grouped (ranking) data folds whole query groups.
generate.cv.folds <- function(nfold, nrows, stratified, label, group,
                              params) {
  if (nfold <= 1) stop("lgb.cv: nfold must be > 1")
  if (!is.null(group)) {
    ng <- length(group)
    gfold <- sample(rep(seq_len(nfold), length.out = ng))
    ends <- cumsum(group)
    starts <- c(1, utils::head(ends, -1) + 1)
    return(lapply(seq_len(nfold), function(k) {
      unlist(lapply(which(gfold == k),
                    function(g) seq(starts[g], ends[g])))
    }))
  }
  obj <- params$objective
  can_stratify <- stratified && !is.null(label) &&
    (is.null(obj) || obj %in% c("binary", "multiclass", "multiclassova",
                                "cross_entropy", "xentropy"))
  if (can_stratify) {
    return(lgb.stratified.folds(label, nfold))
  }
  idx <- sample(nrows)
  split(idx, rep(seq_len(nfold), length.out = nrows))
}

lgb.stratified.folds <- function(y, k = 10) {
  # caret-style stratification exactly like the reference
  # (lgb.cv.R:370-428): numeric labels are quantile-binned into at most
  # 5 magnitude groups first, then each group is balanced across folds
  if (is.numeric(y) && length(unique(y)) > k) {
    cuts <- max(2, min(5, floor(length(y) / k)))
    y <- cut(y, unique(stats::quantile(y, probs = seq(0, 1,
                                                      length.out = cuts))),
             include.lowest = TRUE)
  }
  # sample() on a length-1 vector means sample(1:x) — always index
  resample <- function(x) x[sample.int(length(x))]
  fold_of <- integer(length(y))
  for (cls in unique(y)) {
    members <- which(y == cls)
    fold_of[members] <- resample(rep(seq_len(k),
                                     length.out = length(members)))
  }
  lapply(seq_len(k), function(f) which(fold_of == f))
}

# Fold split that understands query groups: for ranking data the folds
# hold whole groups (generate.cv.folds), so each side keeps the group
# sizes of its own groups in order; plain data goes through slice().
.lgbtpu_cv_split <- function(data, train_idx, test_idx) {
  grp <- data$info$group
  if (is.null(grp)) {
    return(list(train = slice(data, train_idx),
                valid = slice(data, test_idx)))
  }
  row_group <- rep(seq_along(grp), times = grp)
  make <- function(idx) {
    idx <- sort(idx)
    gids <- unique(row_group[idx])
    if (!all(tabulate(row_group[idx], length(grp))[gids] == grp[gids])) {
      stop("lgb.cv: ranking folds must contain whole query groups")
    }
    info <- data$info
    for (f in c("label", "weight", "init_score")) {
      if (!is.null(info[[f]])) info[[f]] <- info[[f]][idx]
    }
    info$group <- grp[gids]
    lgb.Dataset(as.matrix(data$raw_data)[idx, , drop = FALSE],
                params = data$params, colnames = data$colnames,
                categorical_feature = data$categorical_feature,
                info = info)
  }
  list(train = make(train_idx), valid = make(test_idx))
}

.lgbtpu_higher_better <- function(metric) {
  any(startsWith(metric, c("auc", "ndcg", "map")))
}

print.lgb.CVBooster <- function(x, ...) {
  cat("lgb.CVBooster:", length(x$boosters), "folds")
  if (x$best_iter > 0) {
    cat(", best_iter", x$best_iter, "best_score", x$best_score)
  }
  cat("\n")
  invisible(x)
}
