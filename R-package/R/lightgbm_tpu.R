# R binding for lightgbm_tpu.
#
# Architecture: a deliberate thin FILE-based binding over the
# `lightgbm-tpu` CLI (the same engine the Python package drives).  The
# reference R-package binds its C API in-process; here training runs on a
# TPU-backed Python runtime, so the stable exchange surface is the
# reference's own text formats — data files, `key=value` config files and
# model files — which this package reads and writes with base R only.
# Models produced here load in the Python package, the reference CLI and
# the reference R package unchanged, and vice versa.

.lgbtpu_bin <- function() {
  bin <- Sys.getenv("LIGHTGBM_TPU_BIN", "lightgbm-tpu")
  if (Sys.which(bin) == "" && !file.exists(bin)) {
    stop("lightgbm-tpu CLI not found; install the python package ",
         "(pip install lightgbm_tpu) or set LIGHTGBM_TPU_BIN")
  }
  bin
}

.lgbtpu_run <- function(args) {
  bin <- .lgbtpu_bin()
  status <- system2(bin, args = shQuote(args), stdout = TRUE, stderr = TRUE)
  code <- attr(status, "status")
  if (!is.null(code) && code != 0) {
    stop("lightgbm-tpu failed (exit ", code, "):\n",
         paste(utils::tail(status, 20), collapse = "\n"))
  }
  invisible(status)
}

.lgbtpu_write_data <- function(data, label, path) {
  data <- as.matrix(data)
  if (!is.numeric(data)) {
    stop("feature data must be numeric; encode factors/characters first ",
         "(e.g. with model.matrix or as.integer on factor levels)")
  }
  storage.mode(data) <- "double"
  if (is.null(label)) {
    label <- rep(0, nrow(data))
  } else if (is.factor(label) || is.character(label)) {
    stop("label must be numeric (0-based classes for classification); ",
         "got ", class(label)[1],
         " — convert explicitly, e.g. as.integer(factor(y)) - 1")
  }
  out <- cbind(as.numeric(label), data)
  # reference TSV convention: label first, no header, NA -> "nan"
  utils::write.table(out, file = path, sep = "\t", na = "nan",
                     row.names = FALSE, col.names = FALSE)
  invisible(path)
}

# args owned by the binding itself; user params may not override them
.lgbtpu_reserved <- c("task", "data", "output_model", "input_model",
                      "output_result", "valid_data", "num_iterations")

.lgbtpu_params <- function(params) {
  if (length(params) == 0) return(character(0))
  keys <- names(params)
  if (is.null(keys) || any(!nzchar(keys))) {
    stop("params must be a fully named list, e.g. ",
         'list(objective = "binary", num_leaves = 31)')
  }
  bad <- intersect(keys, .lgbtpu_reserved)
  if (length(bad)) {
    stop("params may not override reserved arguments: ",
         paste(bad, collapse = ", "),
         " (use the function arguments / lgb.save instead)")
  }
  vapply(keys,
         function(k) paste0(k, "=", paste(params[[k]], collapse = ",")),
         character(1))
}

#' Train a gradient boosted model.
#'
#' @param data numeric matrix or data.frame of features.
#' @param label numeric response vector (0-based classes for
#'   classification objectives).
#' @param params named list of LightGBM-style parameters
#'   (objective, num_leaves, learning_rate, ...).
#' @param nrounds number of boosting iterations.
#' @param valids optional named list of list(data=, label=) validation sets.
#' @return an object of class `lgbtpu.Booster`.
lgb.train <- function(data, label, params = list(), nrounds = 100L,
                      valids = NULL) {
  work <- tempfile("lgbtpu_")
  dir.create(work)
  on.exit(unlink(work, recursive = TRUE), add = TRUE)
  train_file <- file.path(work, "train.tsv")
  .lgbtpu_write_data(data, label, train_file)
  model_file <- file.path(work, "model.txt")
  args <- c("task=train",
            paste0("data=", train_file),
            paste0("output_model=", model_file),
            paste0("num_iterations=", as.integer(nrounds)),
            .lgbtpu_params(params))
  if (!is.null(valids)) {
    vfiles <- character(0)
    for (i in seq_along(valids)) {
      vf <- file.path(work, paste0("valid_", i, ".tsv"))
      .lgbtpu_write_data(valids[[i]]$data, valids[[i]]$label, vf)
      vfiles <- c(vfiles, vf)
    }
    args <- c(args, paste0("valid_data=", paste(vfiles, collapse = ",")))
  }
  log <- .lgbtpu_run(args)
  structure(
    list(model_string = readLines(model_file), train_log = log),
    class = "lgbtpu.Booster")
}

#' Predict with a trained model.
#'
#' @param model an `lgbtpu.Booster` (or result of [lgb.load]).
#' @param data numeric matrix or data.frame of features.
#' @param raw_score return raw margins instead of transformed output.
#' @return numeric vector (or matrix for multiclass) of predictions.
lgb.predict <- function(model, data, raw_score = FALSE) {
  work <- tempfile("lgbtpu_pred_")
  dir.create(work)
  on.exit(unlink(work, recursive = TRUE), add = TRUE)
  data_file <- file.path(work, "pred.tsv")
  .lgbtpu_write_data(data, NULL, data_file)
  model_file <- file.path(work, "model.txt")
  writeLines(model$model_string, model_file)
  out_file <- file.path(work, "pred_out.txt")
  .lgbtpu_run(c("task=predict",
                paste0("data=", data_file),
                paste0("input_model=", model_file),
                paste0("output_result=", out_file),
                paste0("predict_raw_score=",
                       if (raw_score) "true" else "false")))
  out <- utils::read.table(out_file, header = FALSE)
  if (ncol(out) == 1) out[[1]] else as.matrix(out)
}

#' @export
predict.lgbtpu.Booster <- function(object, newdata, ...) {
  lgb.predict(object, newdata, ...)
}

#' Save a model in the reference text format.
lgb.save <- function(model, filename) {
  writeLines(model$model_string, filename)
  invisible(filename)
}

#' Load a model saved by this package, the Python package, or the
#' reference implementation.
lgb.load <- function(filename) {
  structure(list(model_string = readLines(filename), train_log = NULL),
            class = "lgbtpu.Booster")
}

#' Split-count feature importance parsed from the model file's trailer.
lgb.importance <- function(model) {
  empty <- data.frame(Feature = character(0), Importance = numeric(0),
                      stringsAsFactors = FALSE)
  lines <- model$model_string
  start <- which(lines == "feature importances:")
  if (length(start) == 0 || start[1] >= length(lines)) return(empty)
  body <- lines[seq(start[1] + 1, length(lines))]
  # reference model files append a "parameters:" block after the
  # importances — stop at the first non "name=count" line
  kv_like <- grepl("^[^=]+=[0-9.eE+-]+$", body)
  if (any(!kv_like)) {
    end <- which(!kv_like)[1] - 1L
    if (end < 1L) return(empty)
    body <- body[seq_len(end)]
  }
  body <- body[nzchar(body)]
  if (length(body) == 0) return(empty)
  kv <- strsplit(body, "=", fixed = TRUE)
  data.frame(Feature = vapply(kv, `[`, character(1), 1),
             Importance = as.numeric(vapply(kv, `[`, character(1), 2)),
             stringsAsFactors = FALSE)
}

#' @export
print.lgbtpu.Booster <- function(x, ...) {
  n_trees <- sum(startsWith(x$model_string, "Tree="))
  cat("lightgbm_tpu booster:", n_trees, "trees\n")
  invisible(x)
}
