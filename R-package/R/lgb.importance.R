# lgb.importance: Gain / Cover / Frequency feature importance
# (R-package/R/lgb.importance.R:38-68 surface) computed from the
# per-node table in base R (the reference aggregates the same three
# statistics with data.table).

lgb.importance <- function(model, percentage = TRUE) {
  if (!lgb.is.Booster(model)) {
    stop("'model' has to be an object of class lgb.Booster")
  }
  dt <- lgb.model.dt.tree(model)
  splits <- dt[!is.na(dt$split_index), , drop = FALSE]
  empty <- data.frame(Feature = character(0), Gain = numeric(0),
                      Cover = numeric(0), Frequency = numeric(0),
                      stringsAsFactors = FALSE)
  if (nrow(splits) == 0) return(empty)
  gain <- tapply(splits$split_gain, splits$split_feature, sum)
  cover <- tapply(splits$internal_count, splits$split_feature, sum)
  freq <- tapply(rep(1L, nrow(splits)), splits$split_feature, sum)
  imp <- data.frame(Feature = names(gain),
                    Gain = as.numeric(gain),
                    Cover = as.numeric(cover),
                    Frequency = as.numeric(freq),
                    stringsAsFactors = FALSE)
  imp <- imp[order(imp$Gain, decreasing = TRUE), , drop = FALSE]
  rownames(imp) <- NULL
  if (percentage) {
    imp$Gain <- imp$Gain / sum(imp$Gain)
    imp$Cover <- imp$Cover / sum(imp$Cover)
    imp$Frequency <- imp$Frequency / sum(imp$Frequency)
  }
  imp
}

# lgb.plot.importance (R-package/R/lgb.plot.importance.R surface): a
# horizontal barplot of the top_n measure values in base graphics.
lgb.plot.importance <- function(tree_imp, top_n = 10, measure = "Gain",
                                left_margin = 10, cex = NULL) {
  if (!measure %in% colnames(tree_imp)) {
    stop("lgb.plot.importance: measure must be one of ",
         paste(setdiff(colnames(tree_imp), "Feature"), collapse = ", "))
  }
  top <- utils::head(tree_imp[order(tree_imp[[measure]],
                                    decreasing = TRUE), ], top_n)
  top <- top[rev(seq_len(nrow(top))), , drop = FALSE]
  old <- graphics::par(mar = c(4, left_margin, 2, 1))
  on.exit(graphics::par(old), add = TRUE)
  graphics::barplot(top[[measure]], names.arg = top$Feature, horiz = TRUE,
                    las = 1, main = "Feature importance",
                    xlab = measure, cex.names = cex)
  invisible(top)
}
