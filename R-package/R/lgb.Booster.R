# lgb.Booster: environment-backed S3 model object.
#
# API surface of the reference's R6 Booster
# (R-package/R/lgb.Booster.R:1-871) on the file transport: the object
# owns the model text (the reference's exchange format) plus recorded
# evaluation history; predict shells out to the CLI.  Because the state
# is plain R data — no external pointers — saveRDS/readRDS work
# natively; the reference's special raw-save dance is unnecessary.

.lgbtpu_new_booster <- function(model_string, params = list(),
                                record_evals = list(), best_iter = -1L,
                                best_score = NA_real_) {
  env <- new.env(parent = emptyenv())
  env$model_string <- model_string
  env$params <- params
  env$record_evals <- record_evals
  env$best_iter <- best_iter
  env$best_score <- best_score
  structure(env, class = "lgb.Booster")
}

lgb.load <- function(filename = NULL, model_str = NULL) {
  if (is.null(filename) && is.null(model_str)) {
    stop("lgb.load: either filename or model_str must be given")
  }
  model_string <- if (!is.null(filename)) {
    if (!file.exists(filename)) stop("lgb.load: file does not exist: ",
                                     filename)
    readLines(filename)
  } else {
    strsplit(paste(model_str, collapse = "\n"), "\n", fixed = TRUE)[[1]]
  }
  .lgbtpu_new_booster(model_string)
}

lgb.save <- function(booster, filename, num_iteration = NULL) {
  if (!lgb.is.Booster(booster)) {
    stop("lgb.save: booster should be an lgb.Booster")
  }
  writeLines(.lgbtpu_model_text(booster, num_iteration), filename)
  invisible(booster)
}

# Model text, optionally truncated to the first num_iteration iterations
# (Booster$save_model(num_iteration) semantics; best_iter when -1 is
# requested mirrors the reference's SaveModelToFile contract).  A
# boost_from_average model carries one extra init tree before the
# boosted trees (boosting.py save_model_to_string: (num_iteration + 1)
# * num_class trees kept) — the "boost_from_average" header line flags
# it.
.lgbtpu_has_init_tree <- function(lines) {
  any(lines == "boost_from_average")
}

.lgbtpu_model_text <- function(booster, num_iteration = NULL) {
  lines <- booster$model_string
  if (is.null(num_iteration)) return(lines)
  if (num_iteration <= 0) {
    num_iteration <- if (booster$best_iter > 0) booster$best_iter
                     else .lgbtpu_num_trees(booster)
  }
  nc <- .lgbtpu_num_class(lines)
  keep_trees <- (num_iteration + .lgbtpu_has_init_tree(lines)) * nc
  starts <- grep("^Tree=", lines)
  if (length(starts) <= keep_trees) return(lines)
  head_part <- lines[1:(starts[keep_trees + 1] - 1)]
  # recompute the split-count importance trailer from the KEPT trees
  # (the reference recomputes on save; carrying the full model's
  # counts over would misreport the truncated model)
  feat_names <- .lgbtpu_feature_names(lines)
  counts <- integer(length(feat_names))
  for (kv in .lgbtpu_parse_trees(head_part)) {
    gains <- .lgbtpu_field_num(kv, "split_gain")
    sf <- as.integer(.lgbtpu_field_num(kv, "split_feature")) + 1L
    used <- sf[gains > 0]
    for (f in used) counts[f] <- counts[f] + 1L
  }
  trailer <- "feature importances:"
  ord <- order(counts, decreasing = TRUE)
  ord <- ord[counts[ord] > 0]
  c(head_part, trailer,
    paste0(feat_names[ord], "=", counts[ord]))
}

.lgbtpu_num_trees <- function(booster) {
  lines <- booster$model_string
  n <- length(grep("^Tree=", lines)) - .lgbtpu_has_init_tree(lines)
  nc <- .lgbtpu_num_class(lines)
  as.integer(n / max(nc, 1L))
}

lgb.dump <- function(booster, num_iteration = NULL) {
  if (!lgb.is.Booster(booster)) {
    stop("lgb.dump: booster should be an lgb.Booster")
  }
  work <- .lgbtpu_tmpdir("lgbtpu_dump_")
  on.exit(unlink(work, recursive = TRUE), add = TRUE)
  model_file <- file.path(work, "model.txt")
  writeLines(.lgbtpu_model_text(booster, num_iteration), model_file)
  out_file <- file.path(work, "model.json")
  .lgbtpu_run(c("task=dump_model",
                paste0("input_model=", model_file),
                paste0("convert_model=", out_file)))
  paste(readLines(out_file), collapse = "\n")
}

predict.lgb.Booster <- function(object, data,
                                num_iteration = NULL,
                                rawscore = FALSE,
                                predleaf = FALSE,
                                header = FALSE,
                                reshape = FALSE, ...) {
  if (!lgb.is.Booster(object)) {
    stop("predict.lgb.Booster: object should be an ", sQuote("lgb.Booster"))
  }
  work <- .lgbtpu_tmpdir("lgbtpu_pred_")
  on.exit(unlink(work, recursive = TRUE), add = TRUE)
  data_file <- file.path(work, "pred.tsv")
  if (is.character(data) && length(data) == 1) {
    data_file <- data
  } else {
    .lgbtpu_write_data(data, NULL, data_file)
  }
  model_file <- file.path(work, "model.txt")
  writeLines(object$model_string, model_file)
  out_file <- file.path(work, "pred_out.txt")
  args <- c("task=predict",
            paste0("data=", data_file),
            paste0("input_model=", model_file),
            paste0("output_result=", out_file),
            paste0("header=", if (header) "true" else "false"),
            paste0("predict_raw_score=", if (rawscore) "true" else "false"),
            paste0("predict_leaf_index=", if (predleaf) "true" else "false"))
  if (!is.null(num_iteration)) {
    args <- c(args, paste0("num_iteration_predict=",
                           as.integer(num_iteration)))
  }
  .lgbtpu_run(args)
  out <- as.matrix(utils::read.table(out_file, header = FALSE))
  dimnames(out) <- NULL
  if (predleaf) {
    storage.mode(out) <- "integer"
    return(out)
  }
  if (ncol(out) == 1) return(as.numeric(out[, 1]))
  if (reshape) return(out)
  # reference contract (lgb.Booster.R predict): multiclass output is a
  # flat row-major vector [r0c0, r0c1, ..., r1c0, ...] unless reshape
  as.numeric(t(out))
}

lgb.get.eval.result <- function(booster, data_name, eval_name, iters = NULL,
                                is_err = FALSE) {
  if (!lgb.is.Booster(booster)) {
    stop("lgb.get.eval.result: booster should be an lgb.Booster")
  }
  rec <- booster$record_evals[[data_name]]
  if (is.null(rec)) {
    stop("lgb.get.eval.result: no record for data_name ", sQuote(data_name),
         "; recorded: ", paste(names(booster$record_evals), collapse = ", "))
  }
  entry <- rec[[eval_name]]
  if (is.null(entry)) {
    stop("lgb.get.eval.result: no metric ", sQuote(eval_name),
         " for ", sQuote(data_name),
         "; recorded: ", paste(names(rec), collapse = ", "))
  }
  values <- if (is.list(entry)) {
    if (is_err && !length(entry$eval_err)) {
      stop("lgb.get.eval.result: no error (sd) recorded for ",
           sQuote(eval_name), " (single-run training records no sd; ",
           "use lgb.cv for fold spread)")
    }
    unlist(if (is_err) entry$eval_err else entry$eval)
  } else {
    if (is_err) stop("lgb.get.eval.result: no error (sd) recorded")
    entry
  }
  if (!is.null(iters)) values <- values[iters]
  values
}

print.lgb.Booster <- function(x, ...) {
  cat("lgb.Booster (lightgbm_tpu):", .lgbtpu_num_trees(x), "iterations")
  nc <- .lgbtpu_num_class(x$model_string)
  if (nc > 1) cat(",", nc, "classes")
  if (x$best_iter > 0) cat(", best_iter", x$best_iter)
  cat("\n")
  invisible(x)
}

# The reference needs these wrappers because its Booster holds an
# external pointer that does not survive serialization
# (R-package/R/saveRDS.lgb.Booster.R); ours is plain data, so they are
# thin compatibility shims.
saveRDS.lgb.Booster <- function(object, file = "", ascii = FALSE,
                                version = NULL, compress = TRUE,
                                refhook = NULL, raw = TRUE) {
  saveRDS(object, file = file, ascii = ascii, version = version,
          compress = compress, refhook = refhook)
}

readRDS.lgb.Booster <- function(file = "", refhook = NULL) {
  obj <- readRDS(file = file, refhook = refhook)
  if (!lgb.is.Booster(obj)) {
    stop("readRDS.lgb.Booster: file does not contain an lgb.Booster")
  }
  obj
}
