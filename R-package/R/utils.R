# Internal transport + parsing helpers for the lightgbm_tpu R package.
#
# Architecture: the package binds the `lightgbm-tpu` CLI over the
# reference's own stable TEXT formats — data files, key=value config
# args, `.weight`/`.query`/`.init` side files and model files — using
# base R only.  The reference R-package binds its C API in-process
# (src/lightgbm_R.cpp); here training runs on a TPU-backed Python
# runtime, so a file transport is the honest process boundary.  Models
# produced here load in the Python package, the reference CLI and the
# reference R package unchanged, and vice versa.

.lgbtpu_bin <- function() {
  bin <- Sys.getenv("LIGHTGBM_TPU_BIN", "lightgbm-tpu")
  if (Sys.which(bin) == "" && !file.exists(bin)) {
    stop("lightgbm-tpu CLI not found; install the python package ",
         "(pip install lightgbm_tpu) or set LIGHTGBM_TPU_BIN")
  }
  bin
}

.lgbtpu_run <- function(args) {
  bin <- .lgbtpu_bin()
  out <- system2(bin, args = shQuote(args), stdout = TRUE, stderr = TRUE)
  code <- attr(out, "status")
  if (!is.null(code) && code != 0) {
    stop("lightgbm-tpu failed (exit ", code, "):\n",
         paste(utils::tail(out, 20), collapse = "\n"))
  }
  invisible(out)
}

.lgbtpu_tmpdir <- function(prefix = "lgbtpu_") {
  work <- tempfile(prefix)
  dir.create(work)
  work
}

# Write a feature matrix (+ optional label) in the reference TSV
# convention: label first column, no header, NA -> "nan".
.lgbtpu_write_data <- function(data, label, path) {
  if (is.character(data) && length(data) == 1) {
    # already a file in a reference-readable format
    file.copy(data, path, overwrite = TRUE)
    return(invisible(path))
  }
  data <- as.matrix(data)
  if (!is.numeric(data)) {
    stop("feature data must be numeric; encode factors/characters first ",
         "(e.g. with model.matrix or as.integer on factor levels)")
  }
  storage.mode(data) <- "double"
  if (is.null(label)) {
    label <- rep(0, nrow(data))
  } else if (is.factor(label) || is.character(label)) {
    stop("label must be numeric (0-based classes for classification); got ",
         class(label)[1],
         " - convert explicitly, e.g. as.integer(factor(y)) - 1")
  }
  out <- cbind(as.numeric(label), data)
  utils::write.table(out, file = path, sep = "\t", na = "nan",
                     row.names = FALSE, col.names = FALSE)
  invisible(path)
}

# Reference side-file convention (src/io/metadata.cpp): one value per
# line in <data>.weight / <data>.query / <data>.init next to the data.
# Full double precision — init scores feed continued training and must
# survive the file transport bit-faithfully (%.17g round-trips f64).
.lgbtpu_write_side <- function(path, ext, values) {
  if (is.null(values)) return(invisible(NULL))
  writeLines(sprintf("%.17g", as.numeric(values)), paste0(path, ".", ext))
  invisible(NULL)
}

# args owned by the binding itself; user params may not override them
.lgbtpu_reserved <- c("task", "data", "output_model", "input_model",
                      "output_result", "valid_data", "num_iterations")

.lgbtpu_params <- function(params) {
  if (length(params) == 0) return(character(0))
  keys <- names(params)
  if (is.null(keys) || any(!nzchar(keys))) {
    stop("params must be a fully named list, e.g. ",
         'list(objective = "binary", num_leaves = 31)')
  }
  bad <- intersect(keys, .lgbtpu_reserved)
  if (length(bad)) {
    stop("params may not override reserved arguments: ",
         paste(bad, collapse = ", "),
         " (use the function arguments / lgb.save instead)")
  }
  fmt <- function(v) {
    if (is.logical(v)) v <- ifelse(v, "true", "false")
    paste(v, collapse = ",")
  }
  vapply(keys, function(k) paste0(k, "=", fmt(params[[k]])), character(1))
}

# Parse the CLI's evaluation log lines
#   "[LightGBM-TPU] [INFO] [12]\tvalid_1's auc: 0.83\tvalid_1's l2: ..."
# into list(iter = int vector, sets = list(name -> metric -> numeric)).
.lgbtpu_parse_eval_log <- function(log_lines) {
  hits <- grep("\\[[0-9]+\\]\t", log_lines, value = TRUE)
  iters <- integer(0)
  sets <- list()
  for (line in hits) {
    m <- regmatches(line, regexec("\\[([0-9]+)\\]\t(.*)$", line))[[1]]
    if (length(m) < 3) next
    iters <- c(iters, as.integer(m[2]))
    for (part in strsplit(m[3], "\t", fixed = TRUE)[[1]]) {
      pm <- regmatches(part,
                       regexec("^(.*)'s ([^:]+): ([-0-9.eE+naifNAIF]+)",
                               part))[[1]]
      if (length(pm) < 4) next
      dname <- pm[2]; metric <- pm[3]; val <- as.numeric(pm[4])
      if (is.null(sets[[dname]])) sets[[dname]] <- list()
      if (is.null(sets[[dname]][[metric]])) sets[[dname]][[metric]] <- numeric(0)
      sets[[dname]][[metric]] <- c(sets[[dname]][[metric]], val)
    }
  }
  list(iter = iters, sets = sets)
}

# Split a model file's lines into per-tree blocks of key=value fields.
# Numeric vector fields are space-separated (tree.py GBDT text format,
# identical to the reference's gbdt_model_text.cpp).
.lgbtpu_parse_trees <- function(model_string) {
  starts <- grep("^Tree=", model_string)
  trees <- list()
  for (i in seq_along(starts)) {
    from <- starts[i]
    to <- if (i < length(starts)) starts[i + 1] - 1 else length(model_string)
    block <- model_string[from:to]
    block <- block[nzchar(block) & !startsWith(block, "feature importances")]
    kv <- list()
    for (line in block) {
      eq <- regexpr("=", line, fixed = TRUE)
      if (eq < 0) next
      key <- substr(line, 1, eq - 1)
      kv[[key]] <- substr(line, eq + 1, nchar(line))
    }
    trees[[i]] <- kv
  }
  trees
}

.lgbtpu_field_num <- function(tree_kv, key) {
  raw <- tree_kv[[key]]
  if (is.null(raw) || !nzchar(raw)) return(numeric(0))
  as.numeric(strsplit(trimws(raw), "[[:space:]]+")[[1]])
}

.lgbtpu_feature_names <- function(model_string) {
  line <- grep("^feature_names=", model_string, value = TRUE)
  if (length(line) == 0) return(character(0))
  strsplit(sub("^feature_names=", "", line[1]), " ", fixed = TRUE)[[1]]
}

.lgbtpu_num_class <- function(model_string) {
  line <- grep("^num_class=", model_string, value = TRUE)
  if (length(line) == 0) return(1L)
  as.integer(sub("^num_class=", "", line[1]))
}

lgb.is.Dataset <- function(x) inherits(x, "lgb.Dataset")
lgb.is.Booster <- function(x) inherits(x, "lgb.Booster")
