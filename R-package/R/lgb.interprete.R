# lgb.interprete: per-prediction feature contributions
# (R-package/R/lgb.interprete.R surface in base R).
#
# The contribution of a split node to one prediction is the change in
# model value along the taken branch (child value - node value); the
# leaf path comes from `predict(..., predleaf = TRUE)` through the CLI
# and the node values from lgb.model.dt.tree, so no R-side tree
# routing is needed — the same decomposition the reference computes.

lgb.interprete <- function(model,
                           data,
                           idxset,
                           num_iteration = NULL) {
  tree_dt <- lgb.model.dt.tree(model, num_iteration)
  num_class <- .lgbtpu_num_class(model$model_string)
  leafs <- stats::predict(model, as.matrix(data)[idxset, , drop = FALSE],
                          num_iteration = num_iteration, predleaf = TRUE)
  leafs <- matrix(leafs, nrow = length(idxset))
  lapply(seq_along(idxset), function(i) {
    single.row.interprete(
      tree_dt, num_class,
      matrix(seq_len(ncol(leafs)) - 1L, ncol = num_class, byrow = TRUE),
      matrix(leafs[i, ], ncol = num_class, byrow = TRUE))
  })
}

single.tree.interprete <- function(tree_dt, tree_id, leaf_id) {
  st <- tree_dt[tree_dt$tree_index == tree_id, , drop = FALSE]
  leaves <- st[!is.na(st$leaf_index), , drop = FALSE]
  nodes <- st[!is.na(st$split_index), , drop = FALSE]
  li <- match(leaf_id, leaves$leaf_index)
  value_seq <- leaves$leaf_value[li]
  feature_seq <- character(0)
  parent <- leaves$leaf_parent[li]
  while (!is.na(parent) && parent >= 0) {
    k <- match(parent, nodes$split_index)
    if (is.na(k)) break                       # single-leaf (init) tree
    feature_seq <- c(nodes$split_feature[k], feature_seq)
    value_seq <- c(nodes$internal_value[k], value_seq)
    parent <- nodes$node_parent[k]
  }
  data.frame(Feature = feature_seq,
             Contribution = diff(value_seq),
             stringsAsFactors = FALSE)
}

multiple.tree.interprete <- function(tree_dt, tree_index, leaf_index) {
  parts <- mapply(single.tree.interprete, tree_id = tree_index,
                  leaf_id = leaf_index,
                  MoreArgs = list(tree_dt = tree_dt), SIMPLIFY = FALSE)
  all_dt <- do.call(rbind, parts)
  if (is.null(all_dt) || nrow(all_dt) == 0) {
    return(data.frame(Feature = character(0), Contribution = numeric(0),
                      stringsAsFactors = FALSE))
  }
  agg <- stats::aggregate(Contribution ~ Feature, data = all_dt, FUN = sum)
  agg <- agg[order(abs(agg$Contribution), decreasing = TRUE), , drop = FALSE]
  rownames(agg) <- NULL
  agg
}

single.row.interprete <- function(tree_dt, num_class, tree_index_mat,
                                  leaf_index_mat) {
  per_class <- lapply(seq_len(num_class), function(i) {
    dt <- multiple.tree.interprete(tree_dt, tree_index_mat[, i],
                                   leaf_index_mat[, i])
    if (num_class > 1) {
      names(dt)[names(dt) == "Contribution"] <- paste("Class", i - 1)
    }
    dt
  })
  if (num_class == 1) return(per_class[[1]])
  out <- Reduce(function(x, y) merge(x, y, by = "Feature", all = TRUE),
                per_class)
  out[is.na(out)] <- 0
  out
}

# lgb.plot.interpretation (R-package/R/lgb.plot.interpretation.R
# surface): horizontal barplot(s) of the top_n absolute contributions.
lgb.plot.interpretation <- function(tree_interpretation_dt,
                                    top_n = 10,
                                    cols = 1,
                                    left_margin = 10,
                                    cex = NULL) {
  num_class <- ncol(tree_interpretation_dt) - 1L
  top_n <- min(top_n, nrow(tree_interpretation_dt))
  old <- graphics::par(no.readonly = TRUE)
  on.exit(graphics::par(old), add = TRUE)
  if (num_class > 1) {
    graphics::par(mfrow = c(ceiling(num_class / cols), cols))
  }
  for (j in seq_len(max(num_class, 1)) + 1L) {
    measure <- names(tree_interpretation_dt)[j]
    top <- utils::head(
      tree_interpretation_dt[
        order(abs(tree_interpretation_dt[[j]]), decreasing = TRUE), ,
        drop = FALSE], top_n)
    top <- top[rev(seq_len(nrow(top))), , drop = FALSE]
    graphics::par(mar = c(4, left_margin, 2, 1))
    graphics::barplot(top[[j]], names.arg = top$Feature, horiz = TRUE,
                      las = 1, xlab = "Contribution",
                      main = if (num_class > 1) measure
                             else "Feature interpretation",
                      cex.names = cex)
  }
  invisible(NULL)
}
