# lgb.train: reference-compatible training entry point
# (R-package/R/lgb.train.R:60-253 surface) over the CLI transport.

lgb.train <- function(params = list(),
                      data,
                      nrounds = 10,
                      valids = list(),
                      obj = NULL,
                      eval = NULL,
                      verbose = 1,
                      record = TRUE,
                      eval_freq = 1L,
                      init_model = NULL,
                      colnames = NULL,
                      categorical_feature = NULL,
                      early_stopping_rounds = NULL,
                      callbacks = list(),
                      reset_data = FALSE,
                      ...) {
  params <- append(params, list(...))
  if (is.function(obj) || is.function(params$objective)) {
    stop("lgb.train: custom objective functions cannot cross the CLI ",
         "transport; use a built-in objective name or the Python package")
  }
  if (is.function(eval)) {
    stop("lgb.train: custom eval functions cannot cross the CLI transport; ",
         "use built-in metric names or the Python package")
  }
  if (length(callbacks)) {
    stop("lgb.train: R-side callbacks cannot run inside the CLI process; ",
         "use eval_freq / early_stopping_rounds / record instead")
  }
  if (!is.null(obj)) params$objective <- obj
  if (!is.null(eval)) params$metric <- eval
  if (!lgb.is.Dataset(data)) {
    stop("lgb.train: data must be an lgb.Dataset object")
  }
  if (!is.null(colnames)) dimnames(data) <- list(NULL, colnames)
  if (!is.null(categorical_feature)) {
    lgb.Dataset.set.categorical(data, categorical_feature)
  }

  work <- .lgbtpu_tmpdir("lgbtpu_train_")
  on.exit(unlink(work, recursive = TRUE), add = TRUE)
  train_file <- .lgbtpu_construct_in(data, work, "train")

  # validation sets: the CLI names them valid_1..n in argument order and
  # the training set "training" (is_training_metric); remember the
  # mapping back to the user's names for record_evals
  name_map <- list()
  vfiles <- character(0)
  want_train_metric <- FALSE
  if (length(valids)) {
    vnames <- names(valids)
    if (is.null(vnames) || any(!nzchar(vnames))) {
      stop("lgb.train: valids must be a NAMED list of lgb.Dataset objects")
    }
    vi <- 0L
    for (i in seq_along(valids)) {
      v <- valids[[i]]
      if (!lgb.is.Dataset(v)) {
        stop("lgb.train: valids[[", i, "]] is not an lgb.Dataset")
      }
      if (identical(v, data)) {
        want_train_metric <- TRUE
        name_map[["training"]] <- vnames[i]
      } else {
        vi <- vi + 1L
        vf <- .lgbtpu_construct_in(v, work, paste0("valid_", vi))
        vfiles <- c(vfiles, vf)
        name_map[[paste0("valid_", vi)]] <- vnames[i]
      }
    }
  }

  model_file <- file.path(work, "model.txt")
  cat_idx <- .lgbtpu_cat_indices(data)
  # record_evals AND the early-stopping best-iteration message are
  # parsed from the engine's log, so the CLI must emit info-level
  # output whenever either is needed — system2 captures it, and only
  # verbose >= 1 echoes the eval lines to the R console below
  have_evals <- length(vfiles) > 0 || want_train_metric
  cli_verbose <- if (verbose >= 1 || (record && have_evals)
                     || !is.null(early_stopping_rounds)) 1 else -1
  args <- c("task=train",
            paste0("data=", train_file),
            paste0("output_model=", model_file),
            paste0("num_iterations=", as.integer(nrounds)),
            paste0("verbose=", cli_verbose),
            paste0("output_freq=", as.integer(eval_freq)),
            .lgbtpu_params(params))
  if (length(vfiles)) {
    args <- c(args, paste0("valid_data=", paste(vfiles, collapse = ",")))
  }
  if (want_train_metric) args <- c(args, "is_training_metric=true")
  if (!is.null(cat_idx)) {
    args <- c(args, paste0("categorical_feature=",
                           paste(cat_idx, collapse = ",")))
  }
  if (!is.null(early_stopping_rounds)) {
    args <- c(args, paste0("early_stopping_round=",
                           as.integer(early_stopping_rounds)))
  }
  if (!is.null(init_model)) {
    init_file <- if (lgb.is.Booster(init_model)) {
      f <- file.path(work, "init_model.txt")
      lgb.save(init_model, f)
      f
    } else {
      as.character(init_model)
    }
    args <- c(args, paste0("input_model=", init_file))
  }

  log <- .lgbtpu_run(args)
  if (verbose >= 1) {
    evals <- grep("\\[[0-9]+\\]\t", log, value = TRUE)
    if (length(evals)) cat(evals, sep = "\n")
  }
  booster <- .lgbtpu_new_booster(readLines(model_file), params = params)
  if (record) {
    booster$record_evals <- .lgbtpu_record_evals(log, name_map)
  }
  es <- regmatches(log, regexec("best iteration is: \\[([0-9]+)\\]", log))
  es <- Filter(length, es)
  if (length(es)) {
    booster$best_iter <- as.integer(es[[length(es)]][2])
    # the log holds one entry per LOGGED iteration (eval_freq spacing),
    # so look the score up by iteration NUMBER, not by position
    parsed <- .lgbtpu_parse_eval_log(log)
    if (length(parsed$sets)) {
      first <- parsed$sets[[1]]
      iters <- unique(parsed$iter)
      pos <- match(booster$best_iter, iters)
      if (!is.na(pos) && length(first)) {
        booster$best_score <- first[[1]][pos]
      }
    }
  }
  booster
}

# 0-based categorical indices for the CLI from names or indices
# (reference Dataset$set_categorical_feature accepts both).
.lgbtpu_cat_indices <- function(dataset) {
  cf <- dataset$categorical_feature
  if (is.null(cf) || length(cf) == 0) return(NULL)
  if (is.character(cf)) {
    if (is.null(dataset$colnames)) {
      stop("categorical_feature given by name but the dataset has no ",
           "column names")
    }
    idx <- match(cf, dataset$colnames)
    if (anyNA(idx)) {
      stop("categorical_feature name(s) not found: ",
           paste(cf[is.na(idx)], collapse = ", "))
    }
    idx - 1L
  } else {
    # reference convention: NUMERIC input is 1-based R column numbers
    # (lgb.Dataset.R set_categorical_feature: categorical_feature - 1)
    as.integer(cf) - 1L
  }
}

# CLI eval log -> reference record_evals nesting:
#   record_evals[[data_name]][[metric]]$eval      list of values
#   record_evals[[data_name]][[metric]]$eval_err  list (empty: no sd)
.lgbtpu_record_evals <- function(log, name_map) {
  parsed <- .lgbtpu_parse_eval_log(log)
  rec <- list()
  for (cli_name in names(parsed$sets)) {
    user <- name_map[[cli_name]]
    if (is.null(user)) user <- cli_name
    rec[[user]] <- lapply(parsed$sets[[cli_name]], function(v) {
      list(eval = as.list(v), eval_err = list())
    })
  }
  rec
}
