# lightgbm(): the simple-interface trainer
# (R-package/R/lightgbm.R:6-63 surface).

lightgbm <- function(data,
                     label = NULL,
                     weight = NULL,
                     params = list(),
                     nrounds = 10,
                     verbose = 1,
                     eval_freq = 1L,
                     early_stopping_rounds = NULL,
                     save_name = "lightgbm.model",
                     init_model = NULL,
                     callbacks = list(),
                     ...) {
  dtrain <- data
  if (!lgb.is.Dataset(dtrain)) {
    dtrain <- lgb.Dataset(data, info = list(label = label, weight = weight))
  }
  valids <- list()
  if (verbose > 0) valids$train <- dtrain
  booster <- lgb.train(params = params, data = dtrain, nrounds = nrounds,
                       valids = valids, verbose = verbose,
                       eval_freq = eval_freq,
                       early_stopping_rounds = early_stopping_rounds,
                       init_model = init_model, callbacks = callbacks, ...)
  if (!is.null(save_name) && nzchar(save_name)) {
    lgb.save(booster, save_name)
  }
  booster
}

# The reference's lgb.unloader detaches the package and frees C++
# handles (R-package/R/lgb.unloader.R); with the file transport there
# are no native handles, so only the optional object cleanup applies.
lgb.unloader <- function(restore = TRUE, wipe = FALSE, envir = .GlobalEnv) {
  if (wipe) {
    objs <- ls(envir = envir)
    drop <- objs[vapply(objs, function(o) {
      x <- get(o, envir = envir)
      lgb.is.Booster(x) || lgb.is.Dataset(x) || inherits(x, "lgb.CVBooster")
    }, logical(1))]
    rm(list = drop, envir = envir)
  }
  invisible(NULL)
}
