# lgb.Dataset: environment-backed S3 dataset object.
#
# API surface of the reference's R6 Dataset
# (R-package/R/lgb.Dataset.R:644-1085) on a file transport: the object
# holds the raw matrix plus metadata (label / weight / group /
# init_score, categorical features, colnames, params) and materializes
# reference-format data + side files on construct().

lgb.Dataset <- function(data,
                        params = list(),
                        reference = NULL,
                        colnames = NULL,
                        categorical_feature = NULL,
                        free_raw_data = TRUE,
                        info = list(),
                        ...) {
  info <- utils::modifyList(info, list(...))
  env <- new.env(parent = emptyenv())
  env$raw_data <- data
  env$params <- params
  env$reference <- reference
  env$colnames <- colnames
  env$categorical_feature <- categorical_feature
  env$free_raw_data <- free_raw_data
  env$info <- info
  env$constructed_path <- NULL
  env$version <- 0L
  if (is.null(env$colnames) && is.matrix(data) && !is.null(colnames(data))) {
    env$colnames <- colnames(data)
  }
  structure(env, class = "lgb.Dataset")
}

lgb.Dataset.create.valid <- function(dataset, data, info = list(), ...) {
  if (!lgb.is.Dataset(dataset)) {
    stop("lgb.Dataset.create.valid: input data should be an lgb.Dataset ",
         "object")
  }
  valid <- lgb.Dataset(data,
                       params = dataset$params,
                       reference = dataset,
                       colnames = dataset$colnames,
                       categorical_feature = dataset$categorical_feature,
                       free_raw_data = dataset$free_raw_data,
                       info = utils::modifyList(info, list(...)))
  valid
}

# Materialize the dataset as reference-format files in `dir`; returns
# the data path.  Side files follow src/io/metadata.cpp conventions.
.lgbtpu_construct_in <- function(dataset, dir, name = "data") {
  # already materialized (lgb.Dataset.construct, or an earlier train on
  # the same object) and not invalidated since: reuse the files instead
  # of re-serializing the matrix
  cp <- dataset$constructed_path
  if (!is.null(cp) && file.exists(cp)) return(cp)
  path <- file.path(dir, paste0(name, ".tsv"))
  has_side <- !is.null(dataset$info$weight) ||
    !is.null(dataset$info$group) || !is.null(dataset$info$init_score)
  if (is.character(dataset$raw_data) && length(dataset$raw_data) == 1) {
    if (has_side) {
      # copy into the work dir so side files never land (or clobber
      # anything) next to the user's own data file
      file.copy(dataset$raw_data, path, overwrite = TRUE)
    } else {
      path <- dataset$raw_data    # user-supplied file: use in place
    }
  } else {
    .lgbtpu_write_data(dataset$raw_data, dataset$info$label, path)
  }
  .lgbtpu_write_side(path, "weight", dataset$info$weight)
  .lgbtpu_write_side(path, "query", dataset$info$group)
  .lgbtpu_write_side(path, "init", dataset$info$init_score)
  dataset$constructed_path <- path
  path
}

lgb.Dataset.construct <- function(dataset) {
  if (!lgb.is.Dataset(dataset)) {
    stop("lgb.Dataset.construct: input data should be an lgb.Dataset object")
  }
  if (is.null(dataset$constructed_path)) {
    .lgbtpu_construct_in(dataset, .lgbtpu_tmpdir("lgbtpu_ds_"))
  }
  invisible(dataset)
}

dim.lgb.Dataset <- function(x, ...) {
  if (is.character(x$raw_data)) {
    stop("dim: cannot get dimensions of a file-backed lgb.Dataset before ",
         "training")
  }
  dim(as.matrix(x$raw_data))
}

dimnames.lgb.Dataset <- function(x) {
  list(NULL, x$colnames)
}

`dimnames<-.lgb.Dataset` <- function(x, value) {
  if (!is.list(value) || length(value) != 2) {
    stop("invalid dimnames: must be a list of length 2")
  }
  if (!is.null(value[[2]]) &&
      length(value[[2]]) != dim(x)[2]) {
    stop("invalid dimnames: column name length mismatch")
  }
  x$colnames <- value[[2]]
  x
}

slice <- function(dataset, ...) UseMethod("slice")

slice.lgb.Dataset <- function(dataset, idxset, ...) {
  if (is.character(dataset$raw_data)) {
    stop("slice: cannot slice a file-backed lgb.Dataset")
  }
  info <- dataset$info
  for (k in c("label", "weight", "init_score")) {
    if (!is.null(info[[k]])) info[[k]] <- info[[k]][idxset]
  }
  if (!is.null(info$group)) {
    stop("slice: slicing grouped (ranking) data is not supported; ",
         "re-create the lgb.Dataset from the sliced rows and groups")
  }
  lgb.Dataset(as.matrix(dataset$raw_data)[idxset, , drop = FALSE],
              params = dataset$params,
              colnames = dataset$colnames,
              categorical_feature = dataset$categorical_feature,
              free_raw_data = dataset$free_raw_data,
              info = info)
}

getinfo <- function(dataset, ...) UseMethod("getinfo")

getinfo.lgb.Dataset <- function(dataset, name, ...) {
  if (!is.character(name) || length(name) != 1) {
    stop("getinfo: name must be one of 'label', 'weight', 'group', ",
         "'init_score'")
  }
  dataset$info[[name]]
}

setinfo <- function(dataset, ...) UseMethod("setinfo")

setinfo.lgb.Dataset <- function(dataset, name, info, ...) {
  if (!name %in% c("label", "weight", "group", "init_score")) {
    stop("setinfo: name must be one of 'label', 'weight', 'group', ",
         "'init_score'")
  }
  dataset$info[[name]] <- info
  dataset$constructed_path <- NULL   # invalidate materialized files
  invisible(dataset)
}

lgb.Dataset.set.categorical <- function(dataset, categorical_feature) {
  if (!lgb.is.Dataset(dataset)) {
    stop("lgb.Dataset.set.categorical: input data should be an lgb.Dataset ",
         "object")
  }
  dataset$categorical_feature <- categorical_feature
  dataset$constructed_path <- NULL
  invisible(dataset)
}

lgb.Dataset.set.reference <- function(dataset, reference) {
  if (!lgb.is.Dataset(dataset) || !lgb.is.Dataset(reference)) {
    stop("lgb.Dataset.set.reference: both arguments must be lgb.Dataset ",
         "objects")
  }
  dataset$reference <- reference
  dataset$categorical_feature <- reference$categorical_feature
  dataset$colnames <- reference$colnames
  invisible(dataset)
}

lgb.Dataset.save <- function(dataset, fname) {
  if (!lgb.is.Dataset(dataset)) {
    stop("lgb.Dataset.save: input data should be an lgb.Dataset object")
  }
  .lgbtpu_write_data(dataset$raw_data, dataset$info$label, fname)
  .lgbtpu_write_side(fname, "weight", dataset$info$weight)
  .lgbtpu_write_side(fname, "query", dataset$info$group)
  .lgbtpu_write_side(fname, "init", dataset$info$init_score)
  invisible(dataset)
}

print.lgb.Dataset <- function(x, ...) {
  if (is.character(x$raw_data)) {
    cat("lgb.Dataset (file-backed):", x$raw_data, "\n")
  } else {
    d <- dim(x)
    cat("lgb.Dataset:", d[1], "rows x", d[2], "features\n")
  }
  invisible(x)
}
