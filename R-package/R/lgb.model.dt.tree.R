# lgb.model.dt.tree: flatten a model into a per-node table
# (R-package/R/lgb.model.dt.tree.R surface; returns a base-R data.frame
# with the same columns instead of a data.table — the package has no
# hard dependency on data.table).  Parses the model TEXT directly
# (tree.py / gbdt_model_text.cpp format) rather than the JSON dump, so
# it needs no CLI round-trip.

lgb.model.dt.tree <- function(model, num_iteration = NULL) {
  if (!lgb.is.Booster(model)) {
    stop("lgb.model.dt.tree: model has to be an object of class lgb.Booster")
  }
  lines <- .lgbtpu_model_text(model, num_iteration)
  feat_names <- .lgbtpu_feature_names(lines)
  trees <- .lgbtpu_parse_trees(lines)
  rows <- list()
  for (ti in seq_along(trees)) {
    kv <- trees[[ti]]
    nl <- as.integer(kv[["num_leaves"]])
    sf <- as.integer(.lgbtpu_field_num(kv, "split_feature"))
    gain <- .lgbtpu_field_num(kv, "split_gain")
    thr <- .lgbtpu_field_num(kv, "threshold")
    dec <- as.integer(.lgbtpu_field_num(kv, "decision_type"))
    lc <- as.integer(.lgbtpu_field_num(kv, "left_child"))
    rc <- as.integer(.lgbtpu_field_num(kv, "right_child"))
    ival <- .lgbtpu_field_num(kv, "internal_value")
    icnt <- .lgbtpu_field_num(kv, "internal_count")
    lval <- .lgbtpu_field_num(kv, "leaf_value")
    lcnt <- .lgbtpu_field_num(kv, "leaf_count")
    lpar <- as.integer(.lgbtpu_field_num(kv, "leaf_parent"))
    ni <- nl - 1L
    node_parent <- rep(NA_integer_, max(ni, 0))
    if (ni > 0) {
      for (p in seq_len(ni)) {
        for (child in c(lc[p], rc[p])) {
          if (child >= 0) node_parent[child + 1L] <- p - 1L
        }
      }
    }
    if (ni > 0) {
      rows[[length(rows) + 1]] <- data.frame(
        tree_index = ti - 1L,
        split_index = seq_len(ni) - 1L,
        split_feature = feat_names[sf + 1L],
        node_parent = node_parent,
        leaf_index = NA_integer_,
        leaf_parent = NA_integer_,
        split_gain = gain,
        threshold = thr,
        decision_type = dec,
        internal_value = ival,
        internal_count = icnt,
        leaf_value = NA_real_,
        leaf_count = NA_integer_,
        stringsAsFactors = FALSE)
    }
    rows[[length(rows) + 1]] <- data.frame(
      tree_index = ti - 1L,
      split_index = NA_integer_,
      split_feature = "NA",
      node_parent = NA_integer_,
      leaf_index = seq_len(nl) - 1L,
      leaf_parent = if (length(lpar)) lpar else rep(NA_integer_, nl),
      split_gain = NA_real_,
      threshold = NA_real_,
      decision_type = NA_integer_,
      internal_value = NA_real_,
      internal_count = NA_integer_,
      leaf_value = lval,
      leaf_count = if (length(lcnt)) as.integer(lcnt)
                   else rep(NA_integer_, nl),
      stringsAsFactors = FALSE)
  }
  do.call(rbind, rows)
}
