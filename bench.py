"""Benchmark: Higgs-shaped GBDT training throughput on TPU.

Workload mirrors the reference's headline benchmark config
(docs/GPU-Performance.md:101-117): binary objective, 255 leaves, 255 bins,
min_data_in_leaf=1, min_sum_hessian_in_leaf=100, lr=0.1, 28 dense features.
Rows default to 1M (BENCH_ROWS overrides; the published Higgs is 10.5M —
set BENCH_ROWS=10500000 to reproduce it).

Baseline: the reference v2.0.5 CLI measured on THIS host (1 CPU core,
identical synthetic data/config at 1M rows): 0.4283 s/tree = 2.336 trees/s.
The published numbers use a 28-core Xeon; we scale the measured single-core
throughput linearly by 28 (optimistic for the CPU — LightGBM scales
sublinearly) to get a conservative stand-in: 65.4 trees/s at 1M rows.
Histogram cost is linear in rows, so the baseline is scaled by
(1M / BENCH_ROWS) for other row counts; BENCH_BASELINE_TPS overrides with a
directly measured number (e.g. from the interop-built reference CLI).
``vs_baseline`` = our trees/s divided by that.

Robustness (round-1 failure was an unreachable TPU plugin): the TPU backend
is probed in a SUBPROCESS with a timeout, so a hung tunnel can never hang
the bench; on probe failure the bench falls back to the CPU backend with a
diagnostic on stderr and still prints its JSON line.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

BASELINE_TREES_PER_SEC_1M = 2.336 * 28  # see module docstring


def _probe_backend(timeout_s: int) -> str:
    """Detect the usable jax platform in a throwaway subprocess (a hung TPU
    plugin init then cannot hang us).  Returns 'tpu' or 'cpu'."""
    code = "import jax; print(jax.devices()[0].platform)"
    for attempt in range(2):
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=timeout_s)
            if r.returncode == 0:
                plat = r.stdout.strip().splitlines()[-1].strip()
                if plat:
                    return plat
            sys.stderr.write(
                f"bench: backend probe attempt {attempt + 1} failed "
                f"(rc={r.returncode}): {r.stderr.strip()[-500:]}\n")
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"bench: backend probe attempt {attempt + 1} timed out "
                f"after {timeout_s}s (TPU plugin unreachable?)\n")
    sys.stderr.write("bench: falling back to the CPU backend\n")
    return "cpu"


def make_data(n, f=28, seed=42):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    X[:, ::4] = np.abs(X[:, ::4]) + 0.1
    mask = rng.rand(n, f // 7) < 0.3
    X[:, :f // 7][mask] = 0.0
    w = rng.randn(f) * 0.5
    y = ((X @ w + rng.randn(n)) > 0).astype(np.float32)
    return X, y


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
    n_timed = int(os.environ.get("BENCH_TREES", 10))
    probe_timeout = int(os.environ.get("BENCH_PROBE_TIMEOUT", 120))
    want = os.environ.get("BENCH_PLATFORM")  # force 'cpu' or 'tpu'
    platform = want or _probe_backend(probe_timeout)
    if platform != "tpu":
        os.environ.setdefault(
            "XLA_FLAGS",
            os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=1")
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    if platform != "tpu":
        jax.config.update("jax_platforms", "cpu")
    from lightgbm_tpu.config import config_from_params
    from lightgbm_tpu.data.dataset import construct
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.boosting import create_boosting

    from lightgbm_tpu.utils import log as _log
    _log.set_verbosity(-1)
    platform = jax.devices()[0].platform
    X, y = make_data(n_rows)
    params = {
        "objective": "binary",
        "num_leaves": int(os.environ.get("BENCH_LEAVES", 255)),
        "max_bin": int(os.environ.get("BENCH_MAX_BIN", 255)),
        "min_data_in_leaf": 1,
        "min_sum_hessian_in_leaf": 100,
        "learning_rate": 0.1,
        "verbose": -1,
        "use_pallas": platform == "tpu",
    }
    cfg = config_from_params(params)
    ds = construct(X, cfg, label=y)
    booster = create_boosting(cfg, ds, create_objective(cfg))

    # warmup (compile)
    booster.train_one_iter()
    jax.block_until_ready(booster.scores)
    t0 = time.perf_counter()
    for _ in range(n_timed):
        booster.train_one_iter()
    jax.block_until_ready(booster.scores)
    dt = time.perf_counter() - t0
    trees_per_sec = n_timed / dt

    baseline = float(os.environ.get(
        "BENCH_BASELINE_TPS",
        BASELINE_TREES_PER_SEC_1M * (1_000_000 / n_rows)))
    print(json.dumps({
        "metric": f"higgs-like {n_rows // 1000}k x28 binary GBDT training "
                  f"throughput, {params['num_leaves']} leaves, "
                  f"{params['max_bin']} bins ({platform})",
        "value": round(trees_per_sec, 4),
        "unit": "trees/sec",
        "vs_baseline": round(trees_per_sec / baseline, 4),
    }))


if __name__ == "__main__":
    main()
