"""Benchmark: Higgs-shaped GBDT training throughput on TPU.

Workload mirrors the reference's headline benchmark config
(docs/GPU-Performance.md:101-117): binary objective, 255 leaves, 255 bins,
min_data_in_leaf=1, min_sum_hessian_in_leaf=100, lr=0.1, 28 dense features.
Rows default to 1M (BENCH_ROWS overrides; the published Higgs is 10.5M).

Baseline: the reference v2.0.5 CLI measured on THIS host (1 CPU core,
identical synthetic data/config): 0.4283 s/tree = 2.336 trees/s.  The
published numbers use a 28-core Xeon; we scale the measured single-core
throughput linearly by 28 (optimistic for the CPU — LightGBM scales
sublinearly) to get a conservative stand-in: 65.4 trees/s.
``vs_baseline`` = our trees/s divided by that.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import sys
import time

import numpy as np

BASELINE_TREES_PER_SEC = 2.336 * 28  # see module docstring


def make_data(n, f=28, seed=42):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    X[:, ::4] = np.abs(X[:, ::4]) + 0.1
    mask = rng.rand(n, f // 7) < 0.3
    X[:, :f // 7][mask] = 0.0
    w = rng.randn(f) * 0.5
    y = ((X @ w + rng.randn(n)) > 0).astype(np.float32)
    return X, y


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
    n_timed = int(os.environ.get("BENCH_TREES", 10))
    import jax
    from lightgbm_tpu.config import config_from_params
    from lightgbm_tpu.data.dataset import construct
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.boosting import create_boosting

    from lightgbm_tpu.utils import log as _log
    _log.set_verbosity(-1)
    platform = jax.devices()[0].platform
    X, y = make_data(n_rows)
    params = {
        "objective": "binary",
        "num_leaves": int(os.environ.get("BENCH_LEAVES", 255)),
        "max_bin": int(os.environ.get("BENCH_MAX_BIN", 255)),
        "min_data_in_leaf": 1,
        "min_sum_hessian_in_leaf": 100,
        "learning_rate": 0.1,
        "verbose": -1,
        "use_pallas": platform == "tpu",
    }
    cfg = config_from_params(params)
    ds = construct(X, cfg, label=y)
    booster = create_boosting(cfg, ds, create_objective(cfg))

    # warmup (compile)
    booster.train_one_iter()
    jax.block_until_ready(booster.scores)
    t0 = time.perf_counter()
    for _ in range(n_timed):
        booster.train_one_iter()
    jax.block_until_ready(booster.scores)
    dt = time.perf_counter() - t0
    trees_per_sec = n_timed / dt

    print(json.dumps({
        "metric": f"higgs-like {n_rows // 1000}k x28 binary GBDT training "
                  f"throughput, 255 leaves, 255 bins ({platform})",
        "value": round(trees_per_sec, 4),
        "unit": "trees/sec",
        "vs_baseline": round(trees_per_sec / BASELINE_TREES_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
