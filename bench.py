"""Benchmark: Higgs-shaped GBDT training throughput on TPU.

Workload mirrors the reference's headline benchmark config
(docs/GPU-Performance.md:101-117): binary objective, 255 leaves, 255 bins,
min_data_in_leaf=1, min_sum_hessian_in_leaf=100, lr=0.1, 28 dense features.
Rows default to 1M (BENCH_ROWS overrides; the published Higgs is 10.5M —
set BENCH_ROWS=10500000 to reproduce it).

Shape knobs (the reference's other headline datasets):
  BENCH_FEATURES=2000   Epsilon-shaped wide dense matrix
  BENCH_SPARSITY=0.9    fraction of zero entries in one-hot-style blocks —
                        mutually-exclusive columns that EFB should bundle
                        (Bosch-style sparse regime, GPU-Performance.md:112)

Baseline: the reference v2.0.5 CLI measured on THIS host (1 CPU core,
identical synthetic data/config at 1M rows, marginal cost of trees 2-11 so
load/bin time cancels — scripts/measure_ref_baseline.py, result committed
in docs/ref_baseline_measured.json): 0.3955 s/tree = 2.5285 trees/s.  The
host exposes exactly one CPU, so the published 28-thread rig
(docs/GPU-Performance.md:101-117) cannot be measured here (num_threads=28
on one core was measured too: 1.60 trees/s — oversubscription hurts); we
scale the measured single-core throughput linearly by 28 (optimistic for
the CPU — LightGBM scales sublinearly) to get a conservative stand-in:
70.8 trees/s at 1M rows x 28 features.  Histogram cost is linear in
rows x features, so the baseline scales by
(1M / BENCH_ROWS) * (28 / BENCH_FEATURES) for other shapes;
BENCH_BASELINE_TPS overrides with a directly measured number (e.g. from the
interop-built reference CLI).  ``vs_baseline`` = our trees/s / that.

Robustness: this process is a thin SUPERVISOR — the measured workload runs
in a child subprocess (BENCH_CHILD=1) so a hung TPU tunnel or a Mosaic
compile failure can never take down the bench.  A fallback ladder
  (1) tpu + fused  (gen-2 in-kernel-gather histogram kernel)
  (2) tpu + pallas (gen-1 one-hot kernel — the hardware-proven rung)
  (3) tpu + einsum histograms        (Pallas compile failure)
  (4) cpu + segment_sum histograms   (TPU unreachable / hung)
is walked until a child prints a result line; the final JSON always appears
on stdout, with a "degraded" field naming any fallback taken (round-1
failure was an unreachable TPU plugin; round-2 was a Mosaic compile error
*after* backend init — both are now survivable by construction).
BENCH_FUSED=0 drops the fused rung — the capture playbook's forced-XLA
A/B (bench_1m_xla.json) against the default ladder's headline.
BENCH_MESH_FUSED=1 (with BENCH_MESH=1) swaps the mesh rung's configs for
the gspmd_hist fused-vs-flat A/B pairs (bench_mesh_fused.json).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline",
"telemetry"[, "leaves_sweep", "degraded", "kernel_mismatch"]}.
"leaves_sweep" (cpu rung by default; BENCH_LEAVES_SWEEP=1 to force on
tpu, =0 to disable) is the deep-tree fixed-cost micro-rung: marginal ms
per additional leaf between 31- and 255-leaf trees at <= 200k rows —
the per-split fixed overhead the round-7 work collapsed, tracked per
round.  "serving" (cpu rung by default; BENCH_SERVING=1 to force on tpu,
=0 to disable) is the high-QPS inference micro-rung (docs/SERVING.md):
p50/p99 latency + QPS of the SoA microbatch engine at 1/64/4096-row
batches on the freshly trained model, the speedup over the per-tree
Predictor.predict host loop, and a mixed-size async replay pinned to
zero recompiles via the predict_jit_entries gauge.  The "telemetry" block
carries the OBSERVED histogram-kernel identity (lightgbm_tpu.obs dispatch
counters) — if it disagrees with the rung label the result is marked
degraded + kernel_mismatch so decide_flips.py refuses to compare it.
"metrics_snapshot" embeds the live Prometheus sample map
(obs/metrics.snapshot) next to "telemetry"/"memory" so
scripts/obs_diff.py can regression-diff two rungs at the metrics level.
"model_quality" embeds the obs/model_quality tracker summary of the
measured training (top features by cumulative gain, gain-decay curve) so
bench_history.py can warn on an importance flip between same-config runs.
BENCH_TRACE=<path> additionally writes a Chrome-trace span file for the
measured child (render: `python -m lightgbm_tpu.obs <path>`).

BENCH_MESH=1 switches the whole run to the ``mesh`` rung (docs/
DISTRIBUTED.md): GSPMD-vs-shard_map data-parallel training on a FORCED
8-logical-device host mesh — data/feature/auto (planner) shardings over
200k x 28 and a feature-wide 2k-column shape, with trees/s and the
compiled-HLO collective census (op counts + bytes) embedded per
configuration.  A host-mesh rung by construction (it A/Bs the
formulations, not chip throughput); the capture playbook banks it as
``bench_mesh.json``.

BENCH_STREAMED=1 switches to the ``streamed`` rung: resident-vs-chunked
out-of-core training A/B over an artificial ``hbm_budget`` that forces
the placement pre-flight to leave the binned matrix host-side and
double-buffer it through the device (data/stream.py) — trees/s, rows/s,
the measured pipeline stall fraction and the ``grower_jit_entries``
zero-recompile pin per configuration; the capture playbook banks it as
``bench_streamed.json``.
"""
import json
import os
import subprocess
import sys
import time

# persistent XLA compilation cache: the grower's ~65 s compile (remote
# tunnel) is paid once per (config, shape) EVER — capture stages and
# relaunched bench runs load the executable from disk in seconds.  Set
# before any jax import so the child workload processes inherit it.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))

BASELINE_TREES_PER_SEC_1M = 2.5285 * 28  # see module docstring

# only binning-relevant params key the dataset cache: grower knobs
# (gather_*, partition_impl, ordered_bins, bin packing, pallas_fused, ...)
# never change the constructed dataset, and hashing them would make every
# A/B stage re-bin during a live tunnel window.  INVARIANT (pinned by
# tests/test_bench_keys.py): this set must stay a superset of every
# construction-relevant Config attribute read under lightgbm_tpu/data/ —
# a new construction knob missing here would silently reuse stale cached
# datasets in A/B runs.
BINNING_KEYS = frozenset({
    "enable_bundle", "max_bin", "min_data_in_bin", "use_missing",
    "zero_as_missing", "bin_construct_sample_cnt", "max_conflict_rate",
    "min_data_in_leaf", "data_random_seed"})


def make_data(n, f=28, sparsity=0.0, seed=42):
    import numpy as np
    rng = np.random.RandomState(seed)
    if sparsity > 0.0:
        # Bosch-style regime: dense head + blocks of mutually-exclusive
        # one-hot-ish columns (zero = missing/default) that EFB can bundle.
        f_dense = max(4, f // 10)
        f_sparse = f - f_dense
        X = np.zeros((n, f), dtype=np.float32)
        X[:, :f_dense] = rng.randn(n, f_dense).astype(np.float32)
        group = max(2, int(round(1.0 / max(1e-6, 1.0 - sparsity))))
        n_groups = (f_sparse + group - 1) // group
        hot = rng.randint(0, group + 1, size=(n, n_groups))  # group = "all zero"
        for gi in range(n_groups):      # one-hot indicator columns (2 bins)
            base = f_dense + gi * group
            width = min(group, f - base)
            sel = hot[:, gi]
            idx = np.flatnonzero(sel < width)
            X[idx, base + sel[idx]] = 1.0
        w = rng.randn(f).astype(np.float32) * 0.5
    else:
        X = rng.randn(n, f).astype(np.float32)
        X[:, ::4] = np.abs(X[:, ::4]) + 0.1
        mask = rng.rand(n, max(1, f // 7)) < 0.3
        X[:, :max(1, f // 7)][mask] = 0.0
        w = rng.randn(f) * 0.5
    y = ((X @ w + rng.randn(n)) > 0).astype(np.float32)
    return X, y


def _construct_cached(make_xy, cfg, n_rows, n_feat, sparsity, params):
    """Construct the binned dataset, memoized on disk.

    Dataset construction is deterministic in (shape, sparsity, binning
    params) — on a live TPU tunnel window every second counts, so repeat
    bench runs load the committed-format binary cache (Dataset.save_binary)
    instead of re-binning.  ``make_xy`` is a thunk: on a cache hit the
    synthetic data is never even generated (~20-30 s at the 10.5M shape).
    BENCH_DS_CACHE= (empty) disables; binning-relevant BENCH_EXTRA_PARAMS
    are part of the key.
    """
    from lightgbm_tpu.basic import Dataset
    from lightgbm_tpu.data.dataset import construct
    cache_dir = os.environ.get(
        "BENCH_DS_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".bench_cache"))
    if not cache_dir:
        X, y = make_xy()
        return construct(X, cfg, label=y)
    import hashlib
    from lightgbm_tpu.config import canonicalize_params
    # keys are canonicalized first so aliases/case/whitespace neither miss
    # the BINNING_KEYS filter nor alias a stale entry; the set itself
    # (module constant) mirrors what lightgbm_tpu/data/ actually reads at
    # construction (incl. min_data_in_leaf's trivial-feature pre-filter
    # and the bin-sample seed) and is invariant-checked in CI.
    raw = dict(kv.partition("=")[::2] for kv in filter(
        None, os.environ.get("BENCH_EXTRA_PARAMS", "").split(",")))
    canon = canonicalize_params(raw)
    extras = ",".join(f"{k}={v}" for k, v in sorted(canon.items())
                      if k in BINNING_KEYS)
    xh = hashlib.md5(extras.encode()).hexdigest()[:8] if extras else "0"
    # version salt: a binning-code change must invalidate cached datasets,
    # or the bench would attribute stale-bin numbers to the code under test
    pkg = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "lightgbm_tpu")
    vh = hashlib.md5()
    for rel in ("data/binning.py", "data/bundling.py", "data/dataset.py",
                "native/gbt_native.cpp"):
        with open(os.path.join(pkg, rel), "rb") as f:
            vh.update(f.read())
    bundle_on = str(params.get("enable_bundle", False)).lower() in ("true",
                                                                    "1")
    key = (f"r{n_rows}_f{n_feat}_s{sparsity}_b{params['max_bin']}"
           f"_e{int(bundle_on)}_x{xh}_v{vh.hexdigest()[:8]}")
    path = os.path.join(cache_dir, key + ".bin")
    if os.path.exists(path):
        try:
            ds = Dataset._load_binary_training_data(path)
            sys.stderr.write(f"bench: dataset cache hit {path}\n")
            return ds
        except Exception as e:          # corrupt/stale cache: rebuild
            sys.stderr.write(f"bench: dataset cache unreadable ({e}); "
                             "rebuilding\n")
    X, y = make_xy()
    ds = construct(X, cfg, label=y)
    try:
        os.makedirs(cache_dir, exist_ok=True)
        wrapper = Dataset(None)
        wrapper._constructed = ds
        wrapper.save_binary(path, compress=False)
    except Exception as e:
        sys.stderr.write(f"bench: dataset cache save failed ({e})\n")
    return ds


def _leaves_sweep(params, n_rows, n_feat, sparsity):
    """Deep-tree fixed-cost micro-rung: per-tree time at 31 vs 255 leaves
    on <= 200k rows (CPU-safe), reported as marginal ms per additional
    leaf at fixed N.  This is the quantity the round-7 perf work
    collapsed (carried-state copies + kilobucket padding made it ~70% of
    deep-tree time); embedding it in every BENCH JSON lets the trajectory
    track deep-tree overhead per round.  Runs by default on the cpu rung,
    BENCH_LEAVES_SWEEP=1 forces it on tpu rungs (two extra grower
    compiles), =0 disables."""
    import time

    import jax
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import config_from_params
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.obs.counters import counters as obs_counters

    rows = min(n_rows, 200_000)
    lo, hi = 31, 255
    n_timed = int(os.environ.get("BENCH_LEAVES_SWEEP_TREES", 2))
    ds = None

    def measure(split_find):
        nonlocal ds
        sec = {}
        for leaves in (lo, hi):
            p = dict(params, num_leaves=leaves)
            if split_find is not None:
                p["split_find"] = split_find
            cfg = config_from_params(p)
            if ds is None:      # num_leaves never keys dataset construction
                ds = _construct_cached(
                    lambda: make_data(rows, n_feat, sparsity), cfg, rows,
                    n_feat, sparsity, p)
            booster = create_boosting(cfg, ds, create_objective(cfg))
            booster.train_one_iter()              # warmup (compile)
            jax.block_until_ready(booster.scores)
            t0 = time.perf_counter()
            for _ in range(n_timed):
                booster.train_one_iter()
            jax.block_until_ready(booster.scores)
            sec[leaves] = (time.perf_counter() - t0) / n_timed
        return sec, (sec[hi] - sec[lo]) / (hi - lo) * 1e3

    sec, marginal = measure(None)         # the configured default
    obs_counters.gauge("leaves_sweep_marginal_ms_per_leaf", marginal)
    out = {"rows": rows, "leaves": [lo, hi],
           "split_find": params.get("split_find", "fused"),
           "sec_per_tree": {str(k): round(v, 4) for k, v in sec.items()},
           "marginal_ms_per_leaf": round(marginal, 3)}
    # in-rung split-find A/B (round 8): the chain forced-baseline partner
    # rides the same dataset/process so the pair shares host conditions;
    # BENCH_LEAVES_AB=0 skips the extra two boosters
    if os.environ.get("BENCH_LEAVES_AB", "") != "0" \
            and params.get("split_find", "fused") != "chain":
        sec_c, marginal_c = measure("chain")
        out["chain_sec_per_tree"] = {str(k): round(v, 4)
                                     for k, v in sec_c.items()}
        out["chain_marginal_ms_per_leaf"] = round(marginal_c, 3)
    return out


def _serving_rung(booster, n_feat, sparsity):
    """High-QPS serving micro-rung (docs/SERVING.md): p50/p99 latency and
    QPS of the SoA microbatch engine at 1/64/4096-row batches on the model
    this child just trained, the speedup over the per-tree
    ``Predictor.predict`` host loop, and a mixed-size request replay
    through the async ModelServer pinned to ZERO recompiles via the
    ``predict_jit_entries`` gauge.  Default-on for the cpu rung,
    BENCH_SERVING=1 forces it on tpu, =0 disables."""
    import time

    import numpy as np
    from lightgbm_tpu.inference import jit_entries
    from lightgbm_tpu.obs.counters import counters as obs_counters
    from lightgbm_tpu.serving import ModelServer

    X, _ = make_data(8192, n_feat, sparsity, seed=7)
    X = np.asarray(X, np.float64)
    # the engine exactly as serving would build it ('auto' backend:
    # SoA microbatch executables on an accelerator, the OpenMP C++
    # traversal on a bare-CPU backend) plus a forced-xla twin so the
    # jitted path is measured on every tier, and — when the model packs —
    # the packed-node-word traversal twin (serving_traversal=packed) so
    # the xla-vs-packed headroom is a tracked number per round
    auto_eng = booster.predict_engine(prewarm=True)
    from lightgbm_tpu.inference import PredictEngine
    xla_eng = auto_eng if (auto_eng.backend, auto_eng.traversal) == \
        ("xla", "xla") else \
        PredictEngine(booster.models, booster.num_class,
                      prewarm=True, backend="xla", traversal="xla")
    packed_eng = PredictEngine(booster.models, booster.num_class,
                               prewarm=False, backend="xla",
                               traversal="packed")
    packed_eng = packed_eng.prewarm() if packed_eng.traversal == "packed" \
        else None                      # unpackable model: no packed row
    entries_warm = jit_entries()
    p = booster.predictor()            # engine attached (just built)

    # the displaced baseline: the per-tree host-traversal loop the
    # acceptance bar prices the engine against
    x4 = X[:4096]
    t0 = time.perf_counter()
    p.predict_raw_trees(x4)
    old_s = time.perf_counter() - t0

    out = {"predict_jit_entries": entries_warm,
           "backend": auto_eng.backend,
           "traversal": auto_eng.traversal, "backends": {}}
    engines = {auto_eng.backend: auto_eng}
    if xla_eng is not auto_eng:
        engines["xla"] = xla_eng
    if packed_eng is not None and \
            (auto_eng.backend, auto_eng.traversal) != ("xla", "packed"):
        engines["xla+packed"] = packed_eng
    for name, eng in engines.items():
        buckets = {}
        for b, reps in ((1, 50), (64, 30), (4096, 5)):
            xb = X[:b]
            eng.raw_scores(xb)         # touch (compiled at prewarm)
            lats = []
            t0 = time.perf_counter()
            for _ in range(reps):
                t1 = time.perf_counter()
                eng.raw_scores(xb)
                lats.append((time.perf_counter() - t1) * 1e3)
            total = time.perf_counter() - t0
            lats = np.asarray(lats)
            buckets[str(b)] = {
                "p50_ms": round(float(np.percentile(lats, 50)), 3),
                "p99_ms": round(float(np.percentile(lats, 99)), 3),
                "qps": round(reps * b / total, 1),
            }
        out["backends"][name] = {
            "buckets": buckets,
            "speedup_vs_predict_loop": round(
                buckets["4096"]["qps"] / (4096 / old_s), 2)}
    out["buckets"] = out["backends"][auto_eng.backend]["buckets"]
    out["predict_loop_rows_per_s"] = round(4096 / old_s, 1)
    out["speedup_vs_predict_loop"] = \
        out["backends"][auto_eng.backend]["speedup_vs_predict_loop"]

    # mixed-size replay, twice: through the async server (coalescing, as
    # deployed) and against the forced-xla ladder — the recompile pin
    # must hold on the JITTED path, not just on a backend that never
    # compiles
    rng = np.random.RandomState(3)
    sizes = rng.choice([1, 2, 8, 33, 64, 200, 512, 1111, 4096], size=60)
    for s in sizes:
        xla_eng.raw_scores(X[:int(s)])
    srv = ModelServer(booster=booster,
                      params={"verbose": -1, "latency_budget_ms": 1.0})
    futs = [srv.submit(X[:int(s)]) for s in sizes]
    for f in futs:
        f.result(timeout=300)
    rep = srv.stop()
    out["replay"] = {"requests": rep["requests"], "rows": rep["rows"],
                     "batches": rep["batches"], "qps": rep["qps"],
                     "rows_per_s": rep["rows_per_s"]}
    out["recompiles"] = jit_entries() - entries_warm
    out["zero_recompile"] = out["recompiles"] == 0
    obs_counters.gauge("predict_jit_entries", jit_entries())
    return out


def _mesh_rung_child():
    """The ``mesh`` rung (BENCH_MESH=1): GSPMD-vs-shard_map training on a
    FORCED 8-logical-device host mesh (docs/DISTRIBUTED.md).

    Two shapes — the 200k x 28 deep-tree shape and a feature-wide
    2k-column shape (the histogram-pool-bound regime the sharding
    planner exists for) — each trained under the data / feature / auto
    (planner) GSPMD shardings plus the forced shard_map A/B partner,
    with trees/s AND the compiled-HLO collective census (op counts +
    bytes, ``GBDT.grow_hlo_census``) embedded per configuration.  Always
    a host-mesh CPU rung by construction: the 8 logical devices stand in
    for chips, so the numbers A/B the FORMULATIONS (who inserts the
    collectives, what payloads move), not chip throughput — deciding the
    on-chip default still needs a tunnel window
    (``scripts/decide_flips.py`` renders the pair as coverage)."""
    import time

    import jax
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import config_from_params
    from lightgbm_tpu.obs.counters import counters as obs_counters
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.utils import log as _log

    _log.set_verbosity(-1)
    n_devices = len(jax.devices())
    n_timed = int(os.environ.get("BENCH_MESH_TREES", 1))
    fused_ab = os.environ.get("BENCH_MESH_FUSED") == "1"
    # per-shape sharding sets: feature sharding only makes sense on the
    # wide shape (its histogram pool is the planner's reason to exist),
    # and on the VIRTUAL mesh all 8 devices share one host's cores — the
    # feature sharding of a 28-column shape would just 8x the row scans
    configs_narrow = [
        ("gspmd_data", {"parallel_impl": "gspmd", "mesh_shape": "data"}),
        ("gspmd_auto", {"parallel_impl": "gspmd", "mesh_shape": "auto"}),
        ("shardmap_data", {"parallel_impl": "shardmap"}),
    ]
    configs_wide = [
        ("gspmd_feature", {"parallel_impl": "gspmd",
                           "mesh_shape": "feature"}),
        ("gspmd_auto", {"parallel_impl": "gspmd", "mesh_shape": "auto"}),
        ("shardmap_data", {"parallel_impl": "shardmap"}),
    ]
    if fused_ab:
        # BENCH_MESH_FUSED=1: the gspmd_hist fused-vs-flat A/B
        # (shard_map islands + interpret-mode fused kernel vs pure-XLA
        # scatter-add) on the data mesh AND the 2x4 hybrid mesh, where
        # the island's partials cross the shard-sized reduction; the
        # wide shape rides the feature mesh (2000 cols / 8 shards = 250
        # per device — inside the kernel's 512-col ceiling)
        def _pair(ms):
            return [
                (f"gspmd_flat_{ms}",
                 {"parallel_impl": "gspmd", "mesh_shape": ms,
                  "gspmd_hist": "flat"}),
                (f"gspmd_fused_{ms}",
                 {"parallel_impl": "gspmd", "mesh_shape": ms,
                  "gspmd_hist": "fused"}),
            ]
        configs_narrow = _pair("data") + _pair("2x4")
        configs_wide = _pair("feature")
    shapes = [
        (int(os.environ.get("BENCH_MESH_ROWS", 200_000)),
         int(os.environ.get("BENCH_MESH_FEATURES", 28)),
         int(os.environ.get("BENCH_MESH_LEAVES", 63)), configs_narrow),
        (int(os.environ.get("BENCH_MESH_WIDE_ROWS", 10_000)),
         int(os.environ.get("BENCH_MESH_WIDE_FEATURES", 2000)),
         int(os.environ.get("BENCH_MESH_WIDE_LEAVES", 15)), configs_wide),
    ]
    out_shapes = {}
    headline = None
    for rows, feats, leaves, configs in shapes:
        key = f"{rows // 1000}kx{feats}"
        params = {
            "objective": "binary", "num_leaves": leaves,
            "max_bin": int(os.environ.get("BENCH_MESH_MAX_BIN", 63)),
            "min_data_in_leaf": 1, "min_sum_hessian_in_leaf": 100,
            "learning_rate": 0.1, "verbose": -1, "use_pallas": False,
            "tree_learner": "data",
        }
        ds = None
        rows_out = {}
        for name, extra in configs:
            p = dict(params, **extra)
            cfg = config_from_params(p)
            if ds is None:   # impl/mesh knobs never key construction
                ds = _construct_cached(
                    lambda: make_data(rows, feats, 0.0), cfg, rows, feats,
                    0.0, p)
            try:
                # fresh counters per config: the observed-kernel identity
                # and any layout_downgrade events below belong to THIS
                # configuration, not whatever trained before it
                obs_counters.reset()
                booster = create_boosting(cfg, ds, create_objective(cfg))
                booster.train_one_iter()          # warmup (compile)
                jax.block_until_ready(booster.scores)
                t0 = time.perf_counter()
                for _ in range(n_timed):
                    booster.train_one_iter()
                jax.block_until_ready(booster.scores)
                dt = (time.perf_counter() - t0) / n_timed
                rec = {"trees_per_sec": round(1.0 / dt, 4),
                       "impl": booster._parallel_impl,
                       "observed_kernel": obs_counters.observed_kernel(),
                       "collectives": booster.grow_hlo_census(
                           label=f"{key}:{name}")}
                downs = obs_counters.events("layout_downgrade")
                if downs:
                    rec["downgrades"] = downs
                if booster._gspmd_plan is not None:
                    plan = booster._gspmd_plan
                    rec["mesh"] = f"{plan.data}x{plan.feature}"
                    rec["block_shard_bins"] = plan.block_shard_bins
                rows_out[name] = rec
            except Exception as e:   # one config never kills the rung
                rows_out[name] = {"error": str(e)[:200]}
        g = rows_out.get("gspmd_data") or rows_out.get("gspmd_feature") \
            or {}
        s = rows_out.get("shardmap_data", {})
        if "trees_per_sec" in g and "trees_per_sec" in s:
            rows_out["gspmd_vs_shardmap"] = round(
                g["trees_per_sec"] / s["trees_per_sec"], 3)
        for ms in ("data", "2x4", "feature"):
            fu = rows_out.get(f"gspmd_fused_{ms}", {})
            fl = rows_out.get(f"gspmd_flat_{ms}", {})
            if "trees_per_sec" in fu and "trees_per_sec" in fl:
                rows_out[f"fused_vs_flat_{ms}"] = round(
                    fu["trees_per_sec"] / fl["trees_per_sec"], 3)
                if headline is None and ms == "data":
                    headline = fu["trees_per_sec"]
        out_shapes[key] = rows_out
        if headline is None:
            headline = g.get("trees_per_sec", 0.0)
    result = {
        "metric": (f"mesh gspmd_hist fused-vs-flat A/B "
                   f"(cpu, forced {n_devices}-device host mesh)"
                   if fused_ab else
                   f"mesh GSPMD-vs-shardmap data-parallel training "
                   f"(cpu, forced {n_devices}-device host mesh)"),
        "value": headline or 0.0,
        "unit": "trees/sec",
        "vs_baseline": None,
        "mesh": {"devices": n_devices, "timed_trees": n_timed,
                 "fused_ab": fused_ab, "shapes": out_shapes},
    }
    print(json.dumps(result))


def _streamed_rung_child():
    """The ``streamed`` rung (BENCH_STREAMED=1): resident-vs-chunked
    out-of-core A/B under an ARTIFICIAL hbm_budget (docs/OBSERVABILITY.md
    ``stream_*`` counters, data/stream.py pipeline).

    One shape, two boosters over the SAME binned dataset: the classic
    fully-device-resident baseline, then ``data_stream=auto`` with
    ``hbm_budget`` scaled below the resident predicted peak so the
    pre-flight placement walk MUST leave the binned matrix host-side and
    stream it through the double-buffered block pipeline.  Per config:
    trees/s, rows/s, the measured stall fraction (blocking wait on
    incoming blocks / wall time — the pipeline's overlap evidence), the
    ``grower_jit_entries`` zero-recompile pin across the chunk loop, and
    the planner's ``PlacementPlan``.  A host rung by construction (CPU's
    synchronous dispatch makes the stall fraction a conservative upper
    bound — the TPU's async DMA only hides MORE of the copy); the
    capture playbook banks it as ``bench_streamed.json``."""
    import time

    import jax
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import config_from_params
    from lightgbm_tpu.obs import memory as obs_memory
    from lightgbm_tpu.obs.counters import counters as obs_counters
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.utils import log as _log

    _log.set_verbosity(-1)
    rows = int(os.environ.get("BENCH_STREAMED_ROWS", 400_000))
    feats = int(os.environ.get("BENCH_STREAMED_FEATURES", 28))
    n_timed = int(os.environ.get("BENCH_STREAMED_TREES", 3))
    chunk_pin = int(os.environ.get("BENCH_STREAMED_CHUNK", 0))
    params = {
        "objective": "binary",
        "num_leaves": int(os.environ.get("BENCH_STREAMED_LEAVES", 63)),
        "max_bin": int(os.environ.get("BENCH_STREAMED_MAX_BIN", 63)),
        "min_data_in_leaf": 1, "min_sum_hessian_in_leaf": 100,
        "learning_rate": 0.1, "verbose": -1, "use_pallas": False,
    }
    cfg0 = config_from_params(params)
    ds = _construct_cached(lambda: make_data(rows, feats, 0.0), cfg0,
                           rows, feats, 0.0, params)
    # the artificial budget: resident's predicted peak scaled down so
    # resident refuses but a (possibly halved) chunk pipeline still fits
    pred = obs_memory.predict_hbm(
        rows=rows, features=int(ds.binned.shape[1]),
        bins=params["max_bin"], leaves=params["num_leaves"],
        bin_bytes=int(ds.binned.dtype.itemsize))
    frac = float(os.environ.get("BENCH_STREAMED_BUDGET_FRACTION", 0.7))
    budget = int(pred["peak_bytes"] * frac)
    configs = [
        ("resident", {"data_stream": "resident"}),
        ("chunked", dict({"data_stream": "auto", "hbm_budget": budget},
                         **({"stream_chunk_rows": chunk_pin}
                            if chunk_pin else {}))),
    ]
    out = {}
    for name, extra in configs:
        cfg = config_from_params(dict(params, **extra))
        try:
            obs_counters.reset()
            booster = create_boosting(cfg, ds, create_objective(cfg))
            placements = obs_counters.events("placement_decision")
            booster.train_one_iter()          # warmup (compile)
            jax.block_until_ready(booster.scores)
            streamer = booster._streamer
            if streamer is not None:
                streamer.take_wait_ms()       # drop warmup-pass waits
            gauge_fn = getattr(booster.grow, "_cache_size", None)
            entries_warm = gauge_fn() if gauge_fn else None
            stalls_warm = obs_counters.total("stream_stalls")
            t0 = time.perf_counter()
            for _ in range(n_timed):
                booster.train_one_iter()
            jax.block_until_ready(booster.scores)
            dt = (time.perf_counter() - t0) / n_timed
            rec = {"trees_per_sec": round(1.0 / dt, 4),
                   "rows_per_sec": round(rows / dt, 1)}
            if streamer is not None:
                wait_ms = streamer.take_wait_ms()
                rec["stream_wait_ms_per_tree"] = round(wait_ms / n_timed, 3)
                rec["stall_fraction"] = round(
                    min(1.0, wait_ms / (dt * n_timed * 1e3)), 4)
                rec["stalls"] = int(
                    obs_counters.total("stream_stalls") - stalls_warm)
                rec["blocks"] = streamer.store.num_blocks
                rec["chunk_rows"] = streamer.store.chunk_rows
            if gauge_fn is not None:
                rec["grower_jit_entries"] = gauge_fn()
                rec["zero_recompile"] = \
                    rec["grower_jit_entries"] == entries_warm
            plan = getattr(booster, "_placement", None)
            if plan is not None:
                rec["placement"] = {
                    "mode": plan.mode, "chunk_rows": plan.chunk_rows,
                    "peak_bytes": plan.peak_bytes,
                    "capacity": plan.capacity}
            elif placements:
                rec["placement"] = placements[-1]
            downs = obs_counters.events("layout_downgrade")
            if downs:
                rec["downgrades"] = downs
            out[name] = rec
        except Exception as e:       # one config never kills the rung
            out[name] = {"error": str(e)[:200]}
    r, c = out.get("resident", {}), out.get("chunked", {})
    if "trees_per_sec" in r and "trees_per_sec" in c:
        out["chunked_vs_resident"] = round(
            c["trees_per_sec"] / r["trees_per_sec"], 3)
    result = {
        "metric": (f"streamed out-of-core training A/B "
                   f"({rows // 1000}k x {feats}, artificial hbm_budget, "
                   f"cpu host pipeline)"),
        "value": c.get("trees_per_sec", 0.0),
        "unit": "trees/sec",
        "vs_baseline": None,
        "streamed": {"rows": rows, "features": feats,
                     "timed_trees": n_timed, "hbm_budget": budget,
                     "budget_fraction": frac,
                     "predicted_resident_peak": pred["peak_bytes"],
                     "configs": out},
    }
    print(json.dumps(result))


def child_main():
    """The measured workload.  Runs under BENCH_CHILD with the platform and
    histogram method fixed by the supervisor; prints the result JSON line."""
    platform_want = os.environ["BENCH_CHILD_PLATFORM"]      # 'tpu' | 'cpu'
    mode = os.environ.get("BENCH_CHILD_MODE", "segment")
    if mode == "mesh":
        # the mesh rung runs on a FORCED 8-logical-device host mesh —
        # flags must land before the CPU client is created
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        _mesh_rung_child()
        return
    if mode == "streamed":
        # the streamed rung is a host-pipeline A/B: one device, the
        # binned matrix host-side, blocks flowing through device_put
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        os.environ["JAX_PLATFORMS"] = "cpu"
        _streamed_rung_child()
        return
    #                      fused | einsum | segment (cpu)
    use_pallas = mode == "fused"
    if platform_want == "cpu":
        os.environ["PALLAS_AXON_POOL_IPS"] = ""             # skip axon plugin
        os.environ["JAX_PLATFORMS"] = "cpu"
    n_rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
    n_feat = int(os.environ.get("BENCH_FEATURES", 28))
    sparsity = float(os.environ.get("BENCH_SPARSITY", 0))
    n_timed = int(os.environ.get("BENCH_TREES", 10))
    if platform_want == "cpu":
        # cap the last-resort rung so it finishes inside the stage timeout
        # (vs_baseline stays honest — the baseline scales by rows).  With
        # the segment-sum histogram + localized partition the CPU rung
        # runs ~0.4 trees/s at 1M x 28; histogram work scales with
        # rows x features, so the cap shrinks proportionally for wide
        # shapes (never below 50k rows).
        cap = max(50_000, int(1_000_000 * 28 / max(n_feat, 1)))
        n_rows = int(os.environ.get("BENCH_ROWS_CPU", min(n_rows, cap)))
        n_timed = int(os.environ.get("BENCH_TREES_CPU", min(n_timed, 5)))

    import jax
    if platform_want == "cpu":
        jax.config.update("jax_platforms", "cpu")
    elif jax.devices()[0].platform != "tpu":
        # never let a silently-CPU backend masquerade as a TPU number — the
        # supervisor must see this rung fail and record the fallback
        sys.stderr.write(f"bench child: wanted tpu, got "
                         f"{jax.devices()[0].platform}\n")
        sys.exit(3)
    from lightgbm_tpu.config import config_from_params
    from lightgbm_tpu.data.dataset import construct
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.obs import devprof as obs_devprof
    from lightgbm_tpu.obs import memory as obs_memory
    from lightgbm_tpu.obs import trace as obs_trace
    from lightgbm_tpu.obs.counters import counters as obs_counters
    from lightgbm_tpu.utils import log as _log

    _log.set_verbosity(-1)
    # telemetry: fresh counters per rung so the observed-kernel evidence is
    # THIS child's; BENCH_TRACE collects a span trace alongside the JSON.
    # Memory accounting is always on for the measured child — every bench
    # JSON carries a "memory" block (predicted + measured peak bytes)
    obs_counters.reset()
    bench_trace = os.environ.get("BENCH_TRACE", "")
    # device-time attribution (obs/devprof.py): armed rungs capture
    # profiler windows over dedicated un-timed steady iterations (below)
    # and embed the device_profile block; needs the tracer's
    # TraceAnnotation phase windows, so tracing arms alongside
    devprof_armed = os.environ.get("BENCH_DEVICE_PROFILE", "") == "1"
    profile_iters = int(os.environ.get("BENCH_PROFILE_ITERS", "2") or 2)
    if bench_trace or devprof_armed:
        obs_trace.start(bench_trace or None)
    obs_memory.start()
    # model-quality plane: every bench JSON embeds the tracker summary
    # (top features by cumulative gain, gain-decay curve) so
    # bench_history.py can flag an importance flip between runs at the
    # same config.  Host-side folds over the drain's fetched arrays only.
    from lightgbm_tpu.obs import model_quality as obs_model_quality
    obs_model_quality.start()
    if devprof_armed:
        obs_devprof.start(profile_iters=profile_iters)
    # a skipped TPU (probe failure in the parent) is first-class evidence:
    # the counter rides the embedded metrics_snapshot / any live scrape as
    # lgbm_tpu_probe_failed_total, and bench_history counts the streaks
    if os.environ.get("BENCH_TPU_SKIPPED"):
        obs_counters.inc("probe_failed", stage="tpu_probe")
        obs_counters.event("probe_failed", stage="tpu_probe",
                           detail=os.environ["BENCH_TPU_SKIPPED"][:200])
    platform = jax.devices()[0].platform
    params = {
        "objective": "binary",
        "num_leaves": int(os.environ.get("BENCH_LEAVES", 255)),
        "max_bin": int(os.environ.get("BENCH_MAX_BIN", 255)),
        "min_data_in_leaf": 1,
        "min_sum_hessian_in_leaf": 100,
        "learning_rate": 0.1,
        "verbose": -1,
        "use_pallas": use_pallas and platform == "tpu",
        "pallas_fused": "on" if mode == "fused" and platform == "tpu"
                        else "auto",
        "enable_bundle": sparsity > 0.0,
    }
    # ad-hoc A/B knobs (e.g. BENCH_EXTRA_PARAMS=enable_bin_packing=false)
    for kv in filter(None, os.environ.get("BENCH_EXTRA_PARAMS",
                                          "").split(",")):
        k, _, v = kv.partition("=")
        params[k] = v
    cfg = config_from_params(params)
    t0 = time.perf_counter()
    ds = _construct_cached(lambda: make_data(n_rows, n_feat, sparsity),
                           cfg, n_rows, n_feat, sparsity, params)
    sys.stderr.write(f"bench: construct {time.perf_counter() - t0:.1f}s, "
                     f"{ds.binned.shape[1]} physical cols for {n_feat} "
                     f"features\n")
    booster = create_boosting(cfg, ds, create_objective(cfg))

    t0 = time.perf_counter()
    booster.train_one_iter()          # warmup (compile)
    jax.block_until_ready(booster.scores)
    sys.stderr.write(f"bench: warmup (compile) {time.perf_counter() - t0:.1f}s\n")
    if devprof_armed:
        # devprof windows run over DEDICATED steady iterations so the
        # capture/parse overhead never perturbs the timed loop below
        t0 = time.perf_counter()
        for _ in range(profile_iters):
            booster.train_one_iter()
        jax.block_until_ready(booster.scores)
        sys.stderr.write(f"bench: devprof capture ({profile_iters} iters) "
                         f"{time.perf_counter() - t0:.1f}s\n")
    t0 = time.perf_counter()
    for _ in range(n_timed):
        booster.train_one_iter()
    jax.block_until_ready(booster.scores)
    dt = time.perf_counter() - t0
    trees_per_sec = n_timed / dt
    sys.stderr.write("bench " + booster.timers.report() + "\n")

    link = _link_profile(jax)
    sys.stderr.write(f"bench: link {json.dumps(link)}\n")

    # label from the grower's RESOLVED method, not the requested mode: a
    # fused request that fell back (layout gate) must never be recorded
    # as a fused number
    resolved = booster.grower_cfg.hist_method
    kernel_tag = (f", {resolved}" if platform == "tpu"
                  and resolved == "fused" else "")

    # rung honesty: the telemetry dispatch counters record which kernel the
    # grower ACTUALLY traced.  A disagreement with the resolved label (e.g.
    # a fused request silently downgraded inside jit, or a pallas rung
    # degraded to einsum) marks the rung degraded so decide_flips never
    # compares mislabeled numbers.  The kernel identity is snapshotted
    # BEFORE the leaves-sweep micro-rung trains its extra boosters.
    observed = obs_counters.observed_kernel()
    # split-find identity of the MEASURED training, snapshotted before the
    # leaves-sweep micro-rung trains its extra (possibly chain-forced A/B)
    # boosters into the same counter registry
    split_find_counts = obs_counters.get("split_find_dispatch")

    # model-quality summary of the MEASURED training, snapshotted (and
    # the tracker disarmed) BEFORE the micro-rungs train extra boosters
    _ = booster.models               # drain the async tail into the tracker
    model_quality = obs_model_quality.get_tracker().summary()
    obs_model_quality.stop()

    # device-time attribution block, finalized BEFORE the micro-rungs so
    # it describes the measured training only (obs/devprof.py)
    device_profile = obs_devprof.stop() if devprof_armed else None
    if devprof_armed and not bench_trace:
        # the tracer was armed only to mirror phase windows into the
        # devprof captures — stop it here so its span overhead never rides
        # the leaves-sweep / serving micro-rung numbers below (no path set,
        # so stop() writes nothing and returns None)
        obs_trace.stop()
    if device_profile is not None:
        sys.stderr.write(
            f"bench: devprof captured={device_profile['captured_iterations']}"
            f" attributed={device_profile['attributed_fraction']}"
            f" phases={json.dumps(device_profile['phase_device_ms'])}\n")

    # device-memory evidence, also snapshotted BEFORE the leaves sweep so
    # its extra boosters never inflate the measured number: the predicted
    # peak (obs/memory.predict_hbm fit model, pre-flight recorded it at
    # booster setup) against the measured peak (TPU memory_stats, or the
    # live-array census on the CPU rung — the predicted-vs-measured
    # agreement tests/test_memory.py pins within the documented tolerance)
    mem_monitor = obs_memory.get_memory()
    mem_monitor.sample(site="bench_end")
    pred = getattr(booster, "memory_prediction", None) or \
        obs_memory.predict_hbm(rows=booster.num_data,
                               features=int(ds.binned.shape[1]),
                               bins=params["max_bin"],
                               leaves=params["num_leaves"])
    measured_peak = mem_monitor.measured_peak()
    mem_expected = (pred["peak_bytes"]
                    if mem_monitor.source == "memory_stats"
                    else pred["resident_bytes"])
    memory_block = {
        "predicted_peak_bytes": pred["peak_bytes"],
        "predicted_resident_bytes": pred["resident_bytes"],
        "predicted_components": dict(
            sorted({**pred["residents"], **pred["transients"]}.items(),
                   key=lambda kv: -kv[1])[:6]),
        "measured_peak_bytes": measured_peak,
        "measured_source": mem_monitor.source,
        "measured_vs_predicted": round(measured_peak / mem_expected, 3)
        if mem_expected else None,
        "top_residents": mem_monitor.top_residents(),
        "device_capacity_bytes": obs_memory.device_capacity(),
    }
    sys.stderr.write(f"bench: memory {json.dumps(memory_block)}\n")

    # deep-tree fixed-cost micro-rung (31 vs 255 leaves, <= 200k rows):
    # default on for the cpu rung, opt-in (BENCH_LEAVES_SWEEP=1) on tpu
    sweep_flag = os.environ.get("BENCH_LEAVES_SWEEP", "")
    leaves_sweep = None
    if sweep_flag != "0" and (platform == "cpu" or sweep_flag == "1"):
        try:
            leaves_sweep = _leaves_sweep(params, n_rows, n_feat, sparsity)
            sys.stderr.write(f"bench: leaves_sweep {json.dumps(leaves_sweep)}\n")
        except Exception as e:       # the micro-rung never kills the bench
            leaves_sweep = {"error": str(e)[:200]}

    # serving micro-rung (docs/SERVING.md): engine latency/QPS ladder +
    # zero-recompile replay on the freshly trained model.  Default on for
    # the cpu rung like the leaves sweep; BENCH_SERVING=1 forces on tpu
    serving_flag = os.environ.get("BENCH_SERVING", "")
    serving = None
    if serving_flag != "0" and (platform == "cpu" or serving_flag == "1"):
        try:
            serving = _serving_rung(booster, n_feat, sparsity)
            sys.stderr.write(f"bench: serving {json.dumps(serving)}\n")
        except Exception as e:       # the micro-rung never kills the bench
            serving = {"error": str(e)[:200]}

    # live-metrics view of the measured child (obs/metrics.py): the same
    # flat sample map a GET /metrics scrape would serve, embedded so
    # scripts/obs_diff.py can regression-diff two rungs at the metrics
    # level (decide_flips prints its coverage row)
    from lightgbm_tpu.obs import metrics as obs_metrics
    metrics_snapshot = obs_metrics.snapshot()

    trace_file = obs_trace.stop() if bench_trace else None
    telemetry = {
        "observed_kernel": observed,
        "hist_dispatch": obs_counters.get("hist_dispatch"),
        # split-find identity (round 8): which best-split scan the grower
        # actually traced — decide_flips refuses a split_find A/B whose
        # label disagrees with this
        "split_find_dispatch": split_find_counts,
        "layout_downgrades": obs_counters.events("layout_downgrade"),
    }
    if trace_file:
        telemetry["trace"] = trace_file
    kernel_mismatch = observed is not None and observed != resolved
    if kernel_mismatch:
        sys.stderr.write(f"bench: KERNEL IDENTITY MISMATCH — rung label "
                         f"{resolved}, telemetry observed {observed}\n")

    if "BENCH_BASELINE_TPS" in os.environ:
        # an externally measured baseline is tied to the shape it was
        # measured at (BENCH_BASELINE_ROWS, default: the requested
        # BENCH_ROWS) — rescale if this rung ran a capped shape
        base_rows = int(os.environ.get(
            "BENCH_BASELINE_ROWS", os.environ.get("BENCH_ROWS", 1_000_000)))
        baseline = float(os.environ["BENCH_BASELINE_TPS"]) \
            * (base_rows / n_rows)
    else:
        baseline = (BASELINE_TREES_PER_SEC_1M
                    * (1_000_000 / n_rows) * (28 / n_feat))
    result = {
        "metric": f"higgs-like {n_rows // 1000}k x{n_feat} binary GBDT "
                  f"training throughput, {params['num_leaves']} leaves, "
                  f"{params['max_bin']} bins ({platform}{kernel_tag}"
                  f"{f', sparsity={sparsity}' if sparsity else ''})",
        "value": round(trees_per_sec, 4),
        "unit": "trees/sec",
        "vs_baseline": round(trees_per_sec / baseline, 4),
        "link": link,
        "telemetry": telemetry,
        "memory": memory_block,
        "metrics_snapshot": metrics_snapshot,
        "model_quality": model_quality,
    }
    if device_profile is not None:
        result["device_profile"] = device_profile
        devprof_out = os.environ.get("BENCH_DEVPROF", "")
        if devprof_out:        # capture scripts collect these per rung
            with open(devprof_out, "w") as f:
                json.dump(device_profile, f)
    if leaves_sweep is not None:
        result["leaves_sweep"] = leaves_sweep
    if serving is not None:
        result["serving"] = serving
    if kernel_mismatch:
        result["kernel_mismatch"] = True
        result["degraded"] = (f"kernel identity mismatch: rung label "
                              f"{resolved} but telemetry observed {observed}")
    print(json.dumps(result))


def _link_profile(jax):
    """Measure the host<->device link constants (RTT, pipelined dispatch,
    small device_get) so every bench number carries the line condition it
    was measured under — tunnel windows vary by orders of magnitude and
    numbers are not comparable across rounds without these."""
    import numpy as np
    try:
        f = jax.jit(lambda x: x + 1)
        x = f(np.float32(0))            # compile
        jax.block_until_ready(x)
        t0 = time.perf_counter()
        for _ in range(10):
            jax.block_until_ready(f(x))
        rtt_ms = (time.perf_counter() - t0) / 10 * 1e3
        t0 = time.perf_counter()
        y = x
        for _ in range(100):
            y = f(y)
        jax.block_until_ready(y)
        dispatch_ms = (time.perf_counter() - t0) / 100 * 1e3
        big = jax.device_put(np.zeros((1 << 18,), np.float32))  # 1 MB
        jax.block_until_ready(big)
        t0 = time.perf_counter()
        np.asarray(big)
        get_ms = (time.perf_counter() - t0) * 1e3
        return {"rtt_ms": round(rtt_ms, 3),
                "dispatch_ms": round(dispatch_ms, 3),
                "get_1mb_ms": round(get_ms, 3)}
    except Exception as e:              # never let diagnostics kill the bench
        return {"error": str(e)[:120]}


def _rung_label(platform: str, mode: str) -> str:
    """Human label for a ladder rung: tpu+fused / tpu+pallas / tpu (einsum)
    / cpu — the tpu/cpu spellings predate the fused rung and are kept so
    degradation strings stay comparable across rounds."""
    return f"{platform}+{mode}" if mode == "fused" else platform


_NOISE_MARKERS = (
    # the LLVM cpu-feature dump (one multi-thousand-char line; BENCH_r05
    # banked it as the entire scheduled-run tail)
    "vs host machine features",
    "This could lead to execution errors",
)
_MAX_STDERR_LINE = 400


def _clean_stderr(err: str, limit: int = 4000) -> str:
    """Bound child stderr before passthrough: the scheduled driver banks
    only the LAST 2000 chars of output, so one unbounded diagnostic line
    can evict every real signal.  Known-noise lines are dropped (with a
    stub naming what was dropped), any line is capped, the total bounded."""
    lines = []
    for ln in (err or "").splitlines():
        if any(m in ln for m in _NOISE_MARKERS):
            lines.append(f"[{len(ln)}-char diagnostic dropped: "
                         f"{ln[:80]}...]")
            continue
        if len(ln) > _MAX_STDERR_LINE:
            ln = (ln[:_MAX_STDERR_LINE]
                  + f" ...[{len(ln) - _MAX_STDERR_LINE} chars truncated]")
        lines.append(ln)
    out = "\n".join(lines)
    return out[-limit:]


def _run_child(platform: str, mode: str, timeout_s: int):
    """One rung of the fallback ladder.  Returns ``(result, rung_record)``:
    the parsed JSON dict (or an error string) plus the bounded structured
    ``{rung, rc, tail}`` record the runner block aggregates."""
    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    env["BENCH_CHILD_PLATFORM"] = platform
    env["BENCH_CHILD_MODE"] = mode
    label = _rung_label(platform, mode)
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           capture_output=True, text=True, timeout=timeout_s,
                           env=env)
    except subprocess.TimeoutExpired as e:
        tail = ""
        if e.stderr:
            err = e.stderr if isinstance(e.stderr, str) else e.stderr.decode(
                "utf-8", "replace")
            sys.stderr.write(_clean_stderr(err))
            tail = " last stderr: " + _clean_stderr(err.strip(), 200) \
                .replace("\n", " | ")
        rung = {"rung": label, "rc": None,
                "tail": f"timeout {timeout_s}s{tail}"[-300:]}
        return f"{label}: timeout {timeout_s}s{tail}", rung
    sys.stderr.write(_clean_stderr(r.stderr))
    if r.returncode == 0:
        for line in reversed(r.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return (json.loads(line),
                            {"rung": label, "rc": 0, "tail": ""})
                except json.JSONDecodeError:
                    break
    tail = _clean_stderr((r.stderr or r.stdout).strip(), 300) \
        .replace("\n", " | ")
    rung = {"rung": label, "rc": r.returncode, "tail": tail}
    return f"{label}: rc={r.returncode} {tail}", rung


def _runner_record(rungs, probe_failed: bool) -> dict:
    """The bounded, structured ``{rc, tail, probe_failed}`` runner block
    every parent-side result embeds — the durable form of what the
    scheduled driver's 2000-char output tail can only sample.
    ``scripts/bench_history.py`` counts probe-failure streaks off it."""
    failed = [r for r in rungs if r["rc"] not in (0,)]
    tail = " ; ".join(f"{r['rung']}: rc={r['rc']} {r['tail']}".strip()
                      for r in failed)
    return {"rc": rungs[-1]["rc"] if rungs else None,
            "tail": tail[-600:],
            "probe_failed": bool(probe_failed)}


def _tpu_reachable(timeout_s: int) -> bool:
    """Cheap bounded probe so a HUNG tpu plugin costs ~2 min, not 2 full
    stage timeouts, before the cpu fallback (round-1 failure mode)."""
    code = "import jax; assert jax.devices()[0].platform == 'tpu'"
    for attempt in range(2):
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=timeout_s)
            if r.returncode == 0:
                return True
            sys.stderr.write(f"bench: tpu probe attempt {attempt + 1} failed "
                             f"(rc={r.returncode}): "
                             f"{_clean_stderr(r.stderr.strip(), 300)}\n")
        except subprocess.TimeoutExpired:
            sys.stderr.write(f"bench: tpu probe attempt {attempt + 1} timed "
                             f"out after {timeout_s}s\n")
    return False


def _attach_last_tpu_capture(res: dict) -> None:
    """When the TPU rung degraded, point at the newest COMMITTED on-chip
    bench artifact (docs/tpu_capture_*/bench_1m.json) — clearly labeled as
    evidence from an earlier live-tunnel window, not this run.  The tunnel
    has died mid-session four rounds running; this keeps a dead tunnel at
    measurement time from reading as 'no TPU number exists'."""
    import glob
    here = os.path.dirname(os.path.abspath(__file__))
    best = None
    for p in sorted(glob.glob(os.path.join(
            here, "docs", "tpu_capture_*", "bench_1m.json"))):
        try:
            with open(p) as f:
                d = json.loads(f.read().strip().splitlines()[-1])
            if "(tpu" in d.get("metric", ""):
                best = (os.path.relpath(p, here), d)
        except (OSError, json.JSONDecodeError, IndexError):
            continue
    if best is not None:
        res["last_committed_tpu_capture"] = {
            "note": "measured during an earlier live-tunnel window, "
                    "not this run",
            "artifact": best[0], **best[1]}


def main():
    if os.environ.get("BENCH_CHILD") == "1":
        child_main()
        return
    timeout_s = int(os.environ.get("BENCH_STAGE_TIMEOUT", 3600))
    if os.environ.get("BENCH_MESH") == "1":
        # the mesh rung is its own single-child mode (forced host mesh,
        # GSPMD-vs-shardmap A/B + compiled-HLO collective census) — the
        # supervisor contract (one JSON line, errors survivable) holds
        res, rung = _run_child("cpu", "mesh", timeout_s)
        if isinstance(res, dict):
            print(json.dumps(res))
        else:
            print(json.dumps({
                "metric": "mesh GSPMD-vs-shardmap data-parallel training",
                "value": 0.0, "unit": "trees/sec", "vs_baseline": None,
                "degraded": f"mesh rung failed: {res}",
                "runner": _runner_record([rung], False)}))
        return
    if os.environ.get("BENCH_STREAMED") == "1":
        # the streamed rung: resident-vs-chunked out-of-core A/B over an
        # artificial hbm_budget — same single-child supervisor contract
        res, rung = _run_child("cpu", "streamed", timeout_s)
        if isinstance(res, dict):
            print(json.dumps(res))
        else:
            print(json.dumps({
                "metric": "streamed out-of-core training A/B",
                "value": 0.0, "unit": "trees/sec", "vs_baseline": None,
                "degraded": f"streamed rung failed: {res}",
                "runner": _runner_record([rung], False)}))
        return
    probe_timeout = int(os.environ.get("BENCH_PROBE_TIMEOUT", 120))
    want = os.environ.get("BENCH_PLATFORM")  # force 'cpu' or 'tpu'
    ladder = [("tpu", "fused"), ("tpu", "einsum"), ("cpu", "segment")]
    if want == "cpu":
        ladder = [("cpu", "segment")]
    elif want == "tpu":
        ladder = [("tpu", "fused"), ("tpu", "einsum")]
    if os.environ.get("BENCH_FUSED") == "0":
        # the capture playbook's forced-XLA A/B partner (bench_1m_xla):
        # drop the fused rung so the ladder lands on the einsum reference
        ladder = [r for r in ladder if r[1] != "fused"]
    probe_failed = False
    rungs: list = []
    if ladder[0][0] == "tpu" and not _tpu_reachable(probe_timeout):
        sys.stderr.write("bench: tpu unreachable, skipping tpu rungs\n")
        probe_failed = True
        dropped = " ; ".join(f"{_rung_label(p, q)}: skipped, tpu "
                             "probe failed" for p, q in ladder if p == "tpu")
        ladder = [r for r in ladder if r[0] != "tpu"]
        if not ladder:   # BENCH_PLATFORM=tpu forced but unreachable
            res = {
                "metric": "higgs-like binary GBDT training throughput",
                "value": 0.0, "unit": "trees/sec", "vs_baseline": 0.0,
                "degraded": dropped, "probe_failed": True,
                "runner": _runner_record([], True)}
            _attach_last_tpu_capture(res)
            print(json.dumps(res))
            return
        os.environ["BENCH_TPU_SKIPPED"] = dropped
    errors = []
    if os.environ.get("BENCH_TPU_SKIPPED"):
        probe_failed = True
        errors.append(os.environ["BENCH_TPU_SKIPPED"])
    for i, (platform, mode) in enumerate(ladder):
        res, rung = _run_child(platform, mode, timeout_s)
        rungs.append(rung)
        if isinstance(res, dict):
            if errors:
                # never clobber a child-reported degradation (e.g. the
                # kernel-identity mismatch) — merge it in
                prior = res.get("degraded")
                res["degraded"] = ("fell back to "
                                   f"{_rung_label(platform, mode)}: "
                                   + " ; ".join(errors)
                                   + (f" ; {prior}" if prior else ""))
                _attach_last_tpu_capture(res)
            if probe_failed:
                res["probe_failed"] = True
            res["runner"] = _runner_record(rungs, probe_failed)
            print(json.dumps(res))
            return
        errors.append(res)
        sys.stderr.write(f"bench: rung failed — {res}\n")
    # every rung failed: still print the one JSON line (driver contract)
    res = {
        "metric": "higgs-like binary GBDT training throughput",
        "value": 0.0,
        "unit": "trees/sec",
        "vs_baseline": 0.0,
        "degraded": "all rungs failed: " + " ; ".join(errors),
        "runner": _runner_record(rungs, probe_failed),
    }
    if probe_failed:
        res["probe_failed"] = True
    _attach_last_tpu_capture(res)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
